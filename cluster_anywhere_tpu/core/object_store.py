"""In-process memory store + node-local shared-memory store client.

MemoryStore is the analogue of the reference's CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h): small
objects and inlined task returns, resolved in-process without shm.

ShmObjectStore is the plasma analogue (src/ray/object_manager/plasma/): a
node-local shared-memory arena for large immutable objects, zero-copy mapped
by every process on the node.  Unlike plasma there is no store daemon on the
data path: the *producer* creates and seals a per-object shm segment and
registers it with the head; readers mmap it directly.  Accounting/eviction is
centralized at the head (refcount-based GC).  A native C++ helper
(native/shmstore) accelerates large copies with parallel memcpy when built.
"""

from __future__ import annotations

import mmap
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .errors import ObjectStoreFullError, StaleObjectError, TaskError
from .ids import ObjectID

SHM_DIR = "/dev/shm"

# Arena slices carry an 8-byte seal sequence ahead of the payload; slice
# names embed the same sequence ("arena@off+size#seq").  A reader whose name
# no longer matches the in-memory sequence is holding a RECYCLED slice and
# gets StaleObjectError instead of silently reading another object's bytes
# (the store then re-resolves through the head: GC'd-and-reused, or spilled).
_SLICE_HDR = 8


@dataclass
class _Entry:
    state: str  # "pending" | "value" | "packed" | "shm" | "error"
    value: Any = None
    packed: Optional[bytes] = None
    shm_name: Optional[str] = None
    error: Optional[BaseException] = None
    size: int = 0


class _Waiter:
    """One blocked wait_ready call: a countdown over a pending-oid set.
    Stores do O(1) membership work per arriving object instead of the waiter
    rescanning its whole list per wakeup (which made a 4k-ref get O(N*wakeups)
    in both scans and thread wakeups)."""

    __slots__ = ("pending", "needed", "event")

    def __init__(self, pending: set, needed: int):
        self.pending = pending
        self.needed = needed
        self.event = threading.Event()


class MemoryStore:
    """Thread-safe in-process object table with blocking waits."""

    def __init__(self):
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()
        self._waiters: List[_Waiter] = []

    def _store(self, oid: ObjectID, entry: _Entry):
        with self._lock:
            self._entries[oid] = entry
            for w in self._waiters:
                if oid in w.pending:
                    w.pending.discard(oid)
                    w.needed -= 1
                    if w.needed <= 0:
                        w.event.set()

    def put_value(self, oid: ObjectID, value: Any, size: int = 0):
        self._store(oid, _Entry("value", value=value, size=size))

    def put_packed(self, oid: ObjectID, packed: bytes):
        self._store(oid, _Entry("packed", packed=packed, size=len(packed)))

    def put_shm(self, oid: ObjectID, shm_name: str, size: int):
        self._store(oid, _Entry("shm", shm_name=shm_name, size=size))

    def put_error(self, oid: ObjectID, error: BaseException):
        self._store(oid, _Entry("error", error=error))

    def mark_pending(self, oid: ObjectID):
        with self._lock:
            self._entries.setdefault(oid, _Entry("pending"))

    def reset_pending(self, oid: ObjectID):
        """Force an entry back to pending (lineage reconstruction re-executes
        the creating task and refills it)."""
        with self._lock:
            self._entries[oid] = _Entry("pending")

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            return e is not None and e.state != "pending"

    def get_entry(self, oid: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(oid)

    def wait_ready(self, oids: List[ObjectID], num_returns: int, timeout: Optional[float]) -> Tuple[List[ObjectID], List[ObjectID]]:
        """Block until num_returns of oids are ready (or timeout). Returns
        (ready, not_ready) preserving input order — `wait()` semantics of the
        reference (python/ray/_private/worker.py:2868)."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            pending = {
                o
                for o in oids
                if (e := self._entries.get(o)) is None or e.state == "pending"
            }
            # duplicates in oids count once: needed is in unique-oid units
            n_unique = len(set(oids))
            needed = num_returns - (n_unique - len(pending))
            if needed > len(pending):
                needed = len(pending)
            waiter = _Waiter(pending, needed)
            if needed > 0:
                self._waiters.append(waiter)
        try:
            if waiter.needed > 0:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                waiter.event.wait(remaining)
        finally:
            with self._lock:
                if waiter in self._waiters:
                    self._waiters.remove(waiter)
                pending_set = set(waiter.pending)
        ready_list, rest = [], []
        for o in oids:
            if o not in pending_set and len(ready_list) < num_returns:
                ready_list.append(o)
            else:
                rest.append(o)
        return ready_list, rest

    def delete(self, oid: ObjectID):
        with self._lock:
            self._entries.pop(oid, None)

    def keys(self):
        with self._lock:
            return list(self._entries.keys())


_PAGE = 4096
_ARENA_DEFAULT = 256 * 1024 * 1024  # first arena size
# Objects up to this size ride the pre-faulted arena path (puts pay memcpy
# only); larger ones get dedicated segments.  16 GiB keeps multi-GiB objects
# (the reference's 100 GiB-object envelope is stitched from such puts) off
# the first-touch-fault path.
_ARENA_MAX_OBJ = 1 << 34


def _align_up(n: int, a: int = _PAGE) -> int:
    return (n + a - 1) & ~(a - 1)


class _Arena:
    """One pre-faulted shm file carved into object slices (plasma-style
    arena, design per src/ray/object_manager/plasma/plasma_allocator.h: touch
    pages once up front so puts pay memcpy, not first-touch fault + memcpy;
    freed slices are reused already-hot).

    First-fit free list sorted by offset, coalescing on free.  The owner
    process is the only allocator; readers map the file read-only and slice.
    """

    __slots__ = ("name", "path", "size", "mm", "free", "lock", "_prefault_thread")

    def __init__(self, name: str, path: str, size: int):
        self.name = name
        self.path = path
        self.size = size
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.free: List[Tuple[int, int]] = [(0, size)]  # (offset, size), sorted
        self.lock = threading.Lock()
        # fault pages in the background: a put that outruns the prefault just
        # faults normally; after a few seconds the whole arena is hot
        self._prefault_thread = threading.Thread(
            target=self._prefault, name="ca-arena-prefault", daemon=True
        )
        self._prefault_thread.start()

    def _reserve_range(self, off: int, size: int) -> bool:
        """Carve exactly [off, off+size) out of the free list if fully free."""
        with self.lock:
            for i, (foff, fsz) in enumerate(self.free):
                if foff <= off and off + size <= foff + fsz:
                    self.free.pop(i)
                    if foff < off:
                        self.free.insert(i, (foff, off - foff))
                        i += 1
                    if off + size < foff + fsz:
                        self.free.insert(i, (off + size, foff + fsz - (off + size)))
                    return True
                if foff > off:
                    break
        return False

    def _prefault(self):
        """Touch every free page once.  Chunks are RESERVED through the
        allocator while being zeroed, so concurrent puts can never have their
        freshly written data overwritten (nor allocate a page mid-zero)."""
        stride = 16 * 1024 * 1024
        zeros = b"\x00" * stride
        try:
            mv = memoryview(self.mm)
            for off in range(0, self.size, stride):
                end = min(off + stride, self.size)
                if not self._reserve_range(off, end - off):
                    continue  # (partially) allocated: the writer faulted it
                try:
                    mv[off:end] = zeros[: end - off]
                finally:
                    self.free_slice(off, end - off)
            mv.release()
        except (ValueError, IndexError):
            pass  # arena closed mid-prefault

    def alloc(self, size: int) -> Optional[int]:
        size = _align_up(size)
        with self.lock:
            for i, (off, sz) in enumerate(self.free):
                if sz >= size:
                    if sz == size:
                        self.free.pop(i)
                    else:
                        self.free[i] = (off + size, sz - size)
                    return off
        return None

    def free_slice(self, offset: int, size: int):
        size = _align_up(size)
        with self.lock:
            import bisect

            i = bisect.bisect_left(self.free, (offset, 0))
            self.free.insert(i, (offset, size))
            # coalesce with next, then previous
            if i + 1 < len(self.free) and offset + size == self.free[i + 1][0]:
                self.free[i] = (offset, size + self.free[i + 1][1])
                self.free.pop(i + 1)
            if i > 0 and self.free[i - 1][0] + self.free[i - 1][1] == self.free[i][0]:
                self.free[i - 1] = (
                    self.free[i - 1][0],
                    self.free[i - 1][1] + self.free[i][1],
                )
                self.free.pop(i)

    def close(self):
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass


class ShmObjectStore:
    """Producer/consumer interface to the node-local shared-memory store.

    Objects live as seal-sequenced slices of pre-faulted arena files
    (shm_name "<arena>@<offset>+<size>#<seq>") or, above _ARENA_MAX_OBJ, as
    dedicated sealed segments.  Segment layout = serialization.pack() format,
    written in place behind the 8-byte slice header.

    Memory management (plasma eviction_policy.h / external_storage.py
    analogue): `budget_bytes` caps total arena footprint; when growth would
    exceed it, `spill_cb(bytes_needed)` is invoked (the Worker spills the
    oldest live slices to disk via the head) before falling back to growth.
    """

    def __init__(
        self,
        session_name: str,
        owner_tag: Optional[str] = None,
        node_id: str = "n0",
        budget_bytes: int = 0,
    ):
        self.session_name = session_name
        self.node_id = node_id
        # per-node namespace: objects living in another node's namespace are
        # NOT mapped directly (even when the "nodes" share a host in the
        # simulated cluster) — they go through the node-to-node transfer path
        self.ns = f"{session_name}/{node_id}"
        self.dir = os.path.join(SHM_DIR, self.ns)
        os.makedirs(self.dir, exist_ok=True)
        self._native = None
        self._native_tried = False
        self._open_maps: Dict[str, Tuple[mmap.mmap, int]] = {}
        self._lock = threading.Lock()
        # producer-side arenas (keyed by arena shm_name); owner_tag namespaces
        # this process's arena files so the head can sweep them if it dies
        self._owner_tag = owner_tag or f"p{os.getpid()}"
        self._arenas: Dict[str, _Arena] = {}
        self._arena_seq = 0
        self._grow_lock = threading.Lock()  # one arena creation at a time
        # live slices this process sealed, insertion-ordered (spill picks the
        # oldest): name -> (alloc_offset, alloc_size, oid_bytes, seal_seq)
        self._live_slices: Dict[str, Tuple[int, int, bytes, int]] = {}
        # slices whose payload is still being written (packed locally or
        # filled from the network): NOT spill candidates — the background
        # spiller would persist torn bytes and recycle memory under the
        # writer.  seal_done() graduates them.
        self._writing: set = set()
        self._slice_seq = 0
        self._live_bytes = 0  # sum of live-slice allocations (watermark input)
        # dedicated segments this process sealed (objects > _ARENA_MAX_OBJ or
        # arena-exhausted puts): name -> (size, oid_bytes, seq).  Counted in
        # _live_bytes and offered as spill candidates — a huge-object
        # workload must trip the watermark too, not just the inline wall.
        self._live_segments: Dict[str, Tuple[int, bytes, int]] = {}
        self.budget_bytes = budget_bytes  # 0 = uncapped
        self.spill_cb = None  # set by the Worker; fn(bytes_needed) -> None
        # proactive spill (local_object_manager.h IO-worker analogue): when
        # live bytes cross the high watermark, kick the owner's background
        # spiller (non-blocking) so the hard inline path above stays a last
        # resort and puts don't eat spill latency
        self.spill_kick_cb = None  # fn() -> None, must not block
        self.spill_high_frac = 0.8

    def arena_bytes(self) -> int:
        with self._lock:
            return sum(a.size for a in self._arenas.values())

    def live_bytes(self) -> int:
        with self._lock:
            return self._live_bytes

    def live_slices_oldest_first(self) -> List[Tuple[str, int, bytes]]:
        """Spill-candidate view: (shm_name, payload_size, oid) oldest first.
        Only primary slices qualify — pulled copies are droppable, not
        spillable, and carry an empty oid tag.  Dedicated segments are
        candidates too, interleaved by seal sequence."""
        with self._lock:
            out = [
                (name, alloc - _SLICE_HDR, oid, seq)
                for name, (off, alloc, oid, seq) in self._live_slices.items()
                if oid and name not in self._writing
            ]
            out += [
                (name, size, oid, seq)
                for name, (size, oid, seq) in self._live_segments.items()
                if oid and name not in self._writing
            ]
        out.sort(key=lambda t: t[3])
        return [(name, size, oid) for name, size, oid, _seq in out]

    # -- native acceleration ------------------------------------------------
    def _native_lib(self):
        if not self._native_tried:
            self._native_tried = True
            try:
                from ..native import shmstore_binding

                self._native = shmstore_binding.load()
            except Exception:
                self._native = None
        return self._native

    # -- producer -----------------------------------------------------------
    def name_for(self, oid: ObjectID) -> str:
        return f"{self.ns}/obj_{oid.hex()}"

    def is_local(self, shm_name: str) -> bool:
        """True if this name lives in this node's namespace (directly
        mappable); False means it must be fetched node-to-node.  Spilled
        locations ("spill:<path>") are local when the path is under this
        node's spill directory."""
        if shm_name.startswith("spill:"):
            return f"/spill/{self.node_id}/" in shm_name
        return shm_name.startswith(self.ns + "/")

    def warm(self, capacity: int = _ARENA_DEFAULT):
        """Pre-create (and background-prefault) an arena so first puts pay
        memcpy only — the plasma analogue of pre-allocated store memory."""
        if self.budget_bytes:
            capacity = min(capacity, self.budget_bytes)
        with self._lock:
            if self._arenas:
                return
            self._arena_seq += 1
            name = f"{self.ns}/arena_{self._owner_tag}_{self._arena_seq}"
        try:
            arena = _Arena(name, os.path.join(SHM_DIR, name), capacity)
        except OSError:
            return
        with self._lock:
            self._arenas[name] = arena

    def _try_alloc(self, size: int) -> Optional[Tuple[_Arena, int]]:
        with self._lock:
            arenas = list(self._arenas.values())
        for a in arenas:
            off = a.alloc(size)
            if off is not None:
                return a, off
        return None

    def _arena_alloc(self, size: int) -> Optional[Tuple[_Arena, int]]:
        got = self._try_alloc(size)
        if got is not None:
            return got
        # over-budget growth first tries to spill old slices to disk (the
        # plasma-eviction analogue); only then does the arena set grow
        if (
            self.budget_bytes
            and self.spill_cb is not None
            and self.arena_bytes() + size > self.budget_bytes
        ):
            try:
                self.spill_cb(size)
            except Exception:
                pass
            got = self._try_alloc(size)
            if got is not None:
                return got
        # growth is serialized: concurrent put bursts must not each create a
        # full-size arena, and a prefault thread transiently reserving chunks
        # must not fake an out-of-space condition
        with self._grow_lock:
            got = self._try_alloc(size)  # another thread may have grown
            if got is not None:
                return got
            with self._lock:
                arenas = list(self._arenas.values())
            for a in arenas:  # drain in-flight prefault reservations
                t = a._prefault_thread
                if t is not None and t.is_alive():
                    t.join(timeout=10.0)
            got = self._try_alloc(size)
            if got is not None:
                return got
            # genuinely out of space: new arena, geometric in object size and
            # total footprint so sustained bursts create O(log) arenas.
            # Under a budget, over-budget growth (spill couldn't free a
            # contiguous fit) is sized to the request, not the geometric
            # schedule — the overshoot stays proportional to one object.
            total = sum(a.size for a in arenas)
            cap = max(_ARENA_DEFAULT, total)
            if self.budget_bytes and total + cap > self.budget_bytes:
                cap = max(self.budget_bytes - total, size * 2)
            while cap < size * 2:
                cap *= 2
            with self._lock:
                self._arena_seq += 1
                name = f"{self.ns}/arena_{self._owner_tag}_{self._arena_seq}"
            try:
                arena = _Arena(name, os.path.join(SHM_DIR, name), cap)
            except OSError:
                return None  # /dev/shm exhausted; caller falls back or errors
            with self._lock:
                self._arenas[name] = arena
            off = arena.alloc(size)
            return (arena, off) if off is not None else None

    def _seal_slice(
        self, arena: _Arena, off: int, payload_size: int, oid: ObjectID, primary: bool
    ) -> Tuple[str, memoryview]:
        """Stamp a fresh allocation's seal sequence and register it live.
        Returns (shm_name, payload view)."""
        with self._lock:
            self._slice_seq += 1
            seq = self._slice_seq
        arena.mm[off : off + _SLICE_HDR] = seq.to_bytes(_SLICE_HDR, "little")
        name = f"{arena.name}@{off}+{payload_size}#{seq}"
        alloc = _align_up(payload_size + _SLICE_HDR)
        with self._lock:
            self._live_slices[name] = (
                off, alloc, oid.binary() if primary else b"", seq
            )
            self._live_bytes += alloc
            self._writing.add(name)
        return name, memoryview(arena.mm)[off + _SLICE_HDR : off + _SLICE_HDR + payload_size]

    def seal_done(self, shm_name: str) -> None:
        """The slice's payload is fully written: it becomes a spill candidate,
        and crossing the high watermark kicks the background spiller (AFTER
        the write, so the spiller can never persist torn bytes)."""
        with self._lock:
            self._writing.discard(shm_name)
            over = (
                self.budget_bytes
                and self.spill_kick_cb is not None
                and self._live_bytes > self.budget_bytes * self.spill_high_frac
            )
        if over:
            self.spill_kick_cb()

    def _pack_into(self, mv, data: bytes, raws: List[Any]):
        native = self._native_lib()
        if native is not None:
            serialization_pack_into_native(native, mv, data, raws)
        else:
            serialization.pack_into(mv, data, raws)

    def create_and_pack(self, oid: ObjectID, data: bytes, raws: List[Any]) -> Tuple[str, int]:
        """Write a serialized value into the store. Returns (shm_name, size).
        shm_name addresses either an arena slice or a dedicated segment."""
        size = serialization.packed_size(data, raws)
        if size <= _ARENA_MAX_OBJ:
            got = self._arena_alloc(_align_up(size + _SLICE_HDR))
            if got is not None:
                arena, off = got
                name, mv = self._seal_slice(arena, off, size, oid, primary=True)
                try:
                    self._pack_into(mv, data, raws)
                except BaseException:
                    mv.release()
                    self.free_local(name)  # aborted write: reclaim, don't leak
                    raise
                mv.release()
                self.seal_done(name)
                return name, size
        # dedicated segment path (huge objects, or arena creation failed).
        # Same inline spill wall as _arena_alloc: a burst of huge puts over
        # budget must try to free room before asking /dev/shm for more —
        # the async watermark kick alone may not land in time.
        if (
            self.budget_bytes
            and self.spill_cb is not None
            and self.live_bytes() + size > self.budget_bytes
        ):
            try:
                self.spill_cb(size)
            except Exception:
                pass
        name = self.name_for(oid)
        path = os.path.join(SHM_DIR, name)
        tmp = path + ".tmp"
        try:
            fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        except FileExistsError:
            raise ObjectStoreFullError(f"object {oid} already being written")
        try:
            os.ftruncate(fd, size)
            with mmap.mmap(fd, size) as m:
                mv = memoryview(m)
                self._pack_into(mv, data, raws)
                mv.release()
        except OSError as e:
            os.close(fd)
            os.unlink(tmp)
            raise ObjectStoreFullError(str(e)) from e
        except BaseException:
            # non-OSError pack failure: the O_EXCL tmp has a FIXED name, so
            # leaking it would brick every retry of this oid with
            # "already being written"
            os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.close(fd)
        os.rename(tmp, path)  # atomic seal
        with self._lock:
            self._slice_seq += 1
            self._live_segments[name] = (size, oid.binary(), self._slice_seq)
            self._live_bytes += size
        self.seal_done(name)  # watermark check (never in _writing: no-op discard)
        return name, size

    def create_for_import(self, oid: ObjectID, size: int, primary: bool = False) -> Tuple[str, memoryview]:
        """Allocate local space for a verbatim copy of an object's packed
        bytes (node-to-node transfer, or primary promotion of an inline
        value).  Returns (local shm_name, writable view of exactly `size`
        bytes); the caller writes into the view and releases it."""
        if size <= _ARENA_MAX_OBJ:
            got = self._arena_alloc(_align_up(size + _SLICE_HDR))
            if got is not None:
                arena, off = got
                return self._seal_slice(arena, off, size, oid, primary=primary)
        name = f"{self.ns}/import_{oid.hex()}"
        path = os.path.join(SHM_DIR, name)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            m = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        with self._lock:
            self._open_maps[name] = (m, size)
            # dedicated import segments join the watermark accounting like
            # their arena-sized siblings (_seal_slice); _writing keeps them
            # out of the spill-candidate list until the fill seals
            self._slice_seq += 1
            self._live_segments[name] = (
                size, oid.binary() if primary else b"", self._slice_seq
            )
            self._live_bytes += size
            self._writing.add(name)
        return name, memoryview(m)

    @staticmethod
    def parse_slice(shm_name: str):
        """'arena@off+size#seq' -> (arena_name, off, payload_size, seq).
        seq is 0 for legacy names without a seal sequence."""
        arena_name, _, rest = shm_name.partition("@")
        off_s, _, rest = rest.partition("+")
        size_s, _, seq_s = rest.partition("#")
        return arena_name, int(off_s), int(size_s), int(seq_s or 0)

    def free_local(self, shm_name: str):
        """Owner-side reclaim of an arena slice (called when the head GCs the
        object); no-op for names this process doesn't own.  Idempotent: a
        slice already freed (e.g. spilled synchronously, then the head's
        reclaim broadcast arrives) is skipped — double-free would corrupt the
        coalescing free list.  Dedicated segments this process sealed are
        reclaimed too (unlink + accounting); import segments stay the
        province of abort_import (they hold writable mappings)."""
        if "@" not in shm_name:
            with self._lock:
                seg = self._live_segments.pop(shm_name, None)
                if seg is not None:
                    self._live_bytes -= seg[0]
                self._writing.discard(shm_name)
            if seg is None:
                return  # untracked segment, or already freed
            self.release(shm_name)
            try:
                os.unlink(os.path.join(SHM_DIR, shm_name))
            except OSError:
                pass
            return
        try:
            arena_name, off, size, _seq = self.parse_slice(shm_name)
        except ValueError:
            return
        with self._lock:
            entry = self._live_slices.pop(shm_name, None)
            if entry is not None:
                self._live_bytes -= entry[1]
            self._writing.discard(shm_name)  # free of an aborted write
        if entry is None:
            return  # unknown or already freed
        arena = self._arenas.get(arena_name)
        if arena is None:
            return
        arena.free_slice(entry[0], entry[1])

    def abort_import(self, shm_name: str) -> None:
        """Reclaim an import allocation whose fill failed (dropped pull,
        serialization error): arena slices go through free_local; dedicated
        segments (huge objects — no '@' in the name) unlink their file and
        drop the writable mapping, which free_local deliberately ignores."""
        if "@" in shm_name:
            self.free_local(shm_name)
            return
        with self._lock:
            cached = self._open_maps.pop(shm_name, None)
            seg = self._live_segments.pop(shm_name, None)
            if seg is not None:
                self._live_bytes -= seg[0]
            self._writing.discard(shm_name)
        if cached is not None:
            try:
                cached[0].close()
            except (BufferError, OSError):
                pass  # exported views: the mapping closes when they drop
        try:
            os.unlink(os.path.join(SHM_DIR, shm_name))
        except OSError:
            pass

    def put(self, oid: ObjectID, value: Any) -> Tuple[str, int]:
        data, buffers = serialization.serialize(value)
        return self.create_and_pack(oid, data, [b.raw() for b in buffers])

    # -- consumer -----------------------------------------------------------
    def _map_file(self, file_name: str) -> mmap.mmap:
        """Map a whole shm file (cached; arenas are mapped once per reader)."""
        with self._lock:
            cached = self._open_maps.get(file_name)
            if cached is not None:
                return cached[0]
        # the owner of this arena writes through its own rw mapping
        own = self._arenas.get(file_name)
        if own is not None:
            return own.mm
        path = os.path.join(SHM_DIR, file_name)
        fd = os.open(path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        with self._lock:
            prev = self._open_maps.get(file_name)
            if prev is not None:  # lost a map race; use the winner
                try:
                    m.close()
                except BufferError:
                    pass
                return prev[0]
            self._open_maps[file_name] = (m, size)
        return m

    def open(self, shm_name: str) -> memoryview:
        """Zero-copy read view of an object (arena slice or segment).
        Raises StaleObjectError if the slice was recycled since the name was
        minted (seal sequence mismatch) — the caller re-resolves through the
        head (the object was GC'd+reused, or spilled to disk)."""
        if shm_name.startswith("spill:"):
            return self.open_spill(shm_name[len("spill:"):])
        if "@" in shm_name:
            file_name, off, size, seq = self.parse_slice(shm_name)
            m = self._map_file(file_name)
            if seq:
                cur = int.from_bytes(bytes(m[off : off + _SLICE_HDR]), "little")
                if cur != seq:
                    raise StaleObjectError(
                        f"slice {shm_name} recycled (seq {cur} != {seq})"
                    )
                off += _SLICE_HDR
            return memoryview(m)[off : off + size]
        return memoryview(self._map_file(shm_name))

    def open_spill(self, path: str) -> memoryview:
        """Read view of a spilled object (disk file, serialization.pack
        format).  The mapping keeps the data alive even if the file is
        unlinked by GC while views exist."""
        fd = os.open(path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return memoryview(m)

    def get(self, shm_name: str) -> Any:
        return serialization.unpack(self.open(shm_name))

    def release(self, shm_name: str):
        if "@" in shm_name:
            return  # arena maps are long-lived; slices have no per-reader state
        with self._lock:
            cached = self._open_maps.pop(shm_name, None)
        if cached is not None:
            try:
                cached[0].close()
            except BufferError:
                # still referenced by a live numpy view; keep mapping alive
                with self._lock:
                    self._open_maps[shm_name] = cached

    def unlink(self, shm_name: str):
        if "@" in shm_name:
            self.free_local(shm_name)
            return
        with self._lock:
            tracked = shm_name in self._live_segments
        if tracked:
            self.free_local(shm_name)  # keeps _live_bytes accounting right
            return
        self.release(shm_name)
        try:
            os.unlink(os.path.join(SHM_DIR, shm_name))
        except FileNotFoundError:
            pass

    def cleanup_session(self):
        import shutil

        with self._lock:
            maps = list(self._open_maps.values())
            self._open_maps.clear()
            arenas = list(self._arenas.values())
            self._arenas.clear()
        for m, _ in maps:
            try:
                m.close()
            except BufferError:
                pass
        for a in arenas:
            a.close()
        shutil.rmtree(self.dir, ignore_errors=True)


def serialization_pack_into_native(native, mv: memoryview, data: bytes, raws: List[Any]) -> int:
    """pack_into using the native parallel memcpy for large buffers."""
    import msgpack

    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    hlen = len(header)
    mv[:4] = hlen.to_bytes(4, "big")
    mv[4 : 4 + hlen] = header
    offset = 4 + hlen
    for r in raws:
        offset = serialization._align(offset)
        ln = len(r)
        native.copy_into(mv, offset, r)
        offset += ln
    return offset
