"""In-process memory store + node-local shared-memory store client.

MemoryStore is the analogue of the reference's CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h): small
objects and inlined task returns, resolved in-process without shm.

ShmObjectStore is the plasma analogue (src/ray/object_manager/plasma/): a
node-local shared-memory arena for large immutable objects, zero-copy mapped
by every process on the node.  Unlike plasma there is no store daemon on the
data path: the *producer* creates and seals a per-object shm segment and
registers it with the head; readers mmap it directly.  Accounting/eviction is
centralized at the head (refcount-based GC).  A native C++ helper
(native/shmstore) accelerates large copies with parallel memcpy when built.
"""

from __future__ import annotations

import mmap
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .errors import ObjectStoreFullError, TaskError
from .ids import ObjectID

SHM_DIR = "/dev/shm"


@dataclass
class _Entry:
    state: str  # "pending" | "value" | "packed" | "shm" | "error"
    value: Any = None
    packed: Optional[bytes] = None
    shm_name: Optional[str] = None
    error: Optional[BaseException] = None
    size: int = 0


class MemoryStore:
    """Thread-safe in-process object table with blocking waits."""

    def __init__(self):
        self._entries: Dict[ObjectID, _Entry] = {}
        self._cv = threading.Condition()

    def put_value(self, oid: ObjectID, value: Any, size: int = 0):
        with self._cv:
            self._entries[oid] = _Entry("value", value=value, size=size)
            self._cv.notify_all()

    def put_packed(self, oid: ObjectID, packed: bytes):
        with self._cv:
            self._entries[oid] = _Entry("packed", packed=packed, size=len(packed))
            self._cv.notify_all()

    def put_shm(self, oid: ObjectID, shm_name: str, size: int):
        with self._cv:
            self._entries[oid] = _Entry("shm", shm_name=shm_name, size=size)
            self._cv.notify_all()

    def put_error(self, oid: ObjectID, error: BaseException):
        with self._cv:
            self._entries[oid] = _Entry("error", error=error)
            self._cv.notify_all()

    def mark_pending(self, oid: ObjectID):
        with self._cv:
            self._entries.setdefault(oid, _Entry("pending"))

    def contains(self, oid: ObjectID) -> bool:
        with self._cv:
            e = self._entries.get(oid)
            return e is not None and e.state != "pending"

    def get_entry(self, oid: ObjectID) -> Optional[_Entry]:
        with self._cv:
            return self._entries.get(oid)

    def wait_ready(self, oids: List[ObjectID], num_returns: int, timeout: Optional[float]) -> Tuple[List[ObjectID], List[ObjectID]]:
        """Block until num_returns of oids are ready (or timeout). Returns
        (ready, not_ready) preserving input order — `wait()` semantics of the
        reference (python/ray/_private/worker.py:2868).

        Re-checks only the still-pending subset on each wakeup so waiting on N
        objects is O(N) total, not O(N^2)."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            pending = [
                o for o in oids if (e := self._entries.get(o)) is None or e.state == "pending"
            ]
            while True:
                if len(oids) - len(pending) >= num_returns:
                    break
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._cv.wait(remaining if remaining is None or remaining < 0.25 else 0.25)
                pending = [
                    o
                    for o in pending
                    if (e := self._entries.get(o)) is None or e.state == "pending"
                ]
            pending_set = set(pending)
            ready_list, rest = [], []
            for o in oids:
                if o not in pending_set and len(ready_list) < num_returns:
                    ready_list.append(o)
                else:
                    rest.append(o)
            return ready_list, rest

    def delete(self, oid: ObjectID):
        with self._cv:
            self._entries.pop(oid, None)

    def keys(self):
        with self._cv:
            return list(self._entries.keys())


class ShmObjectStore:
    """Producer/consumer interface to per-object shm segments.

    Segment layout = serialization.pack() format, written in place.
    """

    def __init__(self, session_name: str):
        self.session_name = session_name
        self.dir = os.path.join(SHM_DIR, session_name)
        os.makedirs(self.dir, exist_ok=True)
        self._native = None
        self._native_tried = False
        self._open_maps: Dict[str, Tuple[mmap.mmap, int]] = {}
        self._lock = threading.Lock()

    # -- native acceleration ------------------------------------------------
    def _native_lib(self):
        if not self._native_tried:
            self._native_tried = True
            try:
                from ..native import shmstore_binding

                self._native = shmstore_binding.load()
            except Exception:
                self._native = None
        return self._native

    # -- producer -----------------------------------------------------------
    def name_for(self, oid: ObjectID) -> str:
        return f"{self.session_name}/obj_{oid.hex()}"

    def create_and_pack(self, oid: ObjectID, data: bytes, raws: List[Any]) -> Tuple[str, int]:
        """Write a serialized value into a new sealed segment. Returns
        (shm_name, size)."""
        size = serialization.packed_size(data, raws)
        name = self.name_for(oid)
        path = os.path.join(SHM_DIR, name)
        tmp = path + ".tmp"
        try:
            fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        except FileExistsError:
            raise ObjectStoreFullError(f"object {oid} already being written")
        try:
            os.ftruncate(fd, size)
            with mmap.mmap(fd, size) as m:
                native = self._native_lib()
                mv = memoryview(m)
                if native is not None:
                    serialization_pack_into_native(native, mv, data, raws)
                else:
                    serialization.pack_into(mv, data, raws)
                mv.release()
        except OSError as e:
            os.close(fd)
            os.unlink(tmp)
            raise ObjectStoreFullError(str(e)) from e
        os.close(fd)
        os.rename(tmp, path)  # atomic seal
        return name, size

    def put(self, oid: ObjectID, value: Any) -> Tuple[str, int]:
        data, buffers = serialization.serialize(value)
        return self.create_and_pack(oid, data, [b.raw() for b in buffers])

    # -- consumer -----------------------------------------------------------
    def open(self, shm_name: str) -> memoryview:
        """Map a sealed segment read-only (zero-copy)."""
        with self._lock:
            cached = self._open_maps.get(shm_name)
            if cached is not None:
                return memoryview(cached[0])
        path = os.path.join(SHM_DIR, shm_name)
        fd = os.open(path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        with self._lock:
            self._open_maps[shm_name] = (m, size)
        return memoryview(m)

    def get(self, shm_name: str) -> Any:
        return serialization.unpack(self.open(shm_name))

    def release(self, shm_name: str):
        with self._lock:
            cached = self._open_maps.pop(shm_name, None)
        if cached is not None:
            try:
                cached[0].close()
            except BufferError:
                # still referenced by a live numpy view; keep mapping alive
                with self._lock:
                    self._open_maps[shm_name] = cached

    def unlink(self, shm_name: str):
        self.release(shm_name)
        try:
            os.unlink(os.path.join(SHM_DIR, shm_name))
        except FileNotFoundError:
            pass

    def cleanup_session(self):
        import shutil

        with self._lock:
            maps = list(self._open_maps.values())
            self._open_maps.clear()
        for m, _ in maps:
            try:
                m.close()
            except BufferError:
                pass
        shutil.rmtree(self.dir, ignore_errors=True)


def serialization_pack_into_native(native, mv: memoryview, data: bytes, raws: List[Any]) -> int:
    """pack_into using the native parallel memcpy for large buffers."""
    import msgpack

    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    hlen = len(header)
    mv[:4] = hlen.to_bytes(4, "big")
    mv[4 : 4 + hlen] = header
    offset = 4 + hlen
    for r in raws:
        offset = serialization._align(offset)
        ln = len(r)
        native.copy_into(mv, offset, r)
        offset += ln
    return offset
