"""Scheduling strategy objects (analogue of python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

from typing import Dict, Optional, Union


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = 0,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class SpreadSchedulingStrategy:
    pass


# ---------------------------------------------------------------------------
# node-label scheduling (python/ray/util/scheduling_strategies.py:135
# NodeLabelSchedulingStrategy + In/NotIn/Exists/DoesNotExist operators)
# ---------------------------------------------------------------------------


class In:
    def __init__(self, *values: str):
        if not values:
            raise ValueError("In() needs at least one value")
        self.values = [str(v) for v in values]


class NotIn:
    def __init__(self, *values: str):
        if not values:
            raise ValueError("NotIn() needs at least one value")
        self.values = [str(v) for v in values]


class Exists:
    pass


class DoesNotExist:
    pass


LabelCondition = Union[In, NotIn, Exists, DoesNotExist, str]


def _cond_wire(cond: LabelCondition) -> dict:
    """Wire form consumed by scheduling.match_labels.  A bare string is
    shorthand for In(value)."""
    if isinstance(cond, str):
        return {"op": "in", "values": [cond]}
    if isinstance(cond, In):
        return {"op": "in", "values": cond.values}
    if isinstance(cond, NotIn):
        return {"op": "!in", "values": cond.values}
    if isinstance(cond, Exists):
        return {"op": "exists"}
    if isinstance(cond, DoesNotExist):
        return {"op": "!exists"}
    raise TypeError(f"label condition must be In/NotIn/Exists/DoesNotExist/str, got {cond!r}")


def selector_wire(selector: Optional[Dict[str, LabelCondition]]) -> Optional[dict]:
    if not selector:
        return None
    return {str(k): _cond_wire(v) for k, v in selector.items()}


class NodeLabelSchedulingStrategy:
    """Schedule onto nodes whose labels satisfy `hard` (required), preferring
    nodes that also satisfy `soft`.  On TPU clusters the auto-populated
    labels (ca.io/tpu-generation, ca.io/tpu-pod-type, ca.io/tpu-slice-name,
    ca.io/tpu-worker-id, ...) make this the natural slice/topology targeting
    vocabulary."""

    def __init__(
        self,
        hard: Optional[Dict[str, LabelCondition]] = None,
        soft: Optional[Dict[str, LabelCondition]] = None,
    ):
        if not hard and not soft:
            raise ValueError("NodeLabelSchedulingStrategy needs hard and/or soft constraints")
        self.hard = hard or {}
        self.soft = soft or {}

    def to_wire(self) -> dict:
        return {
            "type": "NODE_LABEL",
            "hard": selector_wire(self.hard),
            "soft": selector_wire(self.soft),
        }
