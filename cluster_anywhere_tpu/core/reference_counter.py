"""Local reference counting with batched release notifications.

Round-1 scope of the reference's distributed ReferenceCounter
(src/ray/core_worker/reference_count.h): per-process local refcounts for every
ObjectRef handle; when the local count for an object hits zero the release is
batched and flushed to the head, which maintains the cluster-wide count and
unlinks shared-memory segments at zero.  The full borrowing ledger
(AddBorrowedObject / WaitForRefRemoved worker<->worker pubsub) is scheduled
for the multi-node milestone.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .ids import ObjectID


class ReferenceCounter:
    def __init__(self, flush_cb: Optional[Callable[[List[bytes], List[bytes]], None]] = None):
        self._counts: Dict[ObjectID, int] = {}
        self._lock = threading.Lock()
        self._pending_inc: List[bytes] = []
        self._pending_dec: List[bytes] = []
        self._flush_cb = flush_cb
        # objects this process owns (created here); owner keeps data alive
        # until cluster count drops to zero.
        self._owned: set = set()
        # called (outside the lock) when an object's local count reaches 0 —
        # the worker evicts its read-cache entry so value pins can release
        self._on_zero: Optional[Callable[[ObjectID], None]] = None

    def set_flush_cb(self, cb):
        self._flush_cb = cb

    def set_on_zero(self, cb: Callable[[ObjectID], None]):
        self._on_zero = cb

    def add_owned(self, oid: ObjectID):
        with self._lock:
            self._owned.add(oid)

    def remove_owned(self, oid: ObjectID):
        with self._lock:
            self._owned.discard(oid)

    def add_local_ref(self, oid: ObjectID) -> int:
        """Returns the new count (1 = this ref revived the object locally)."""
        with self._lock:
            n = self._counts.get(oid, 0)
            self._counts[oid] = n + 1
            if n == 0:
                self._pending_inc.append(oid.binary())
            return n + 1

    def remove_local_ref(self, oid: ObjectID):
        flush = None
        zero = False
        with self._lock:
            n = self._counts.get(oid, 0) - 1
            if n <= 0:
                self._counts.pop(oid, None)
                self._pending_dec.append(oid.binary())
                zero = True
                if len(self._pending_dec) >= 64:
                    flush = self._take_pending_locked()
            else:
                self._counts[oid] = n
        if zero and self._on_zero is not None:
            try:
                self._on_zero(oid)
            except Exception:
                pass
        if flush and self._flush_cb:
            self._flush_cb(*flush)

    def _take_pending_locked(self):
        inc, dec = self._pending_inc, self._pending_dec
        self._pending_inc, self._pending_dec = [], []
        return inc, dec

    def flush(self):
        with self._lock:
            inc, dec = self._take_pending_locked()
        if (inc or dec) and self._flush_cb:
            self._flush_cb(inc, dec)

    def local_count(self, oid: ObjectID) -> int:
        with self._lock:
            return self._counts.get(oid, 0)

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._owned
