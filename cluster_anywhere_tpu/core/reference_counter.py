"""Local reference counting with batched release notifications.

Per-process HALF of the reference's distributed ReferenceCounter
(src/ray/core_worker/reference_count.h): local refcounts for every ObjectRef
handle, with zero-crossings batched into inc/dec updates.  Where those
updates SETTLE is the ownership plane's concern (core/ownership.py +
worker.py routing): for objects this process owns they land directly in its
OwnerLedger; for borrowed objects they flow to the owner process's ledger
over a direct connection (the AddBorrowedObject / WaitForRefRemoved
worker<->worker protocol, owner-resident form); the head is only the
fallback when an owner is unknown, unreachable, or dead — and the failover
arbiter that adopts a dead owner's ledger from its last synced digest.
(The round-1 note that deferred the borrowing ledger "for the multi-node
milestone" is settled: this IS that milestone.)
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .ids import ObjectID


class ReferenceCounter:
    def __init__(self, flush_cb: Optional[Callable[[List[bytes], List[bytes]], None]] = None):
        self._counts: Dict[ObjectID, int] = {}
        self._lock = threading.Lock()
        self._pending_inc: List[bytes] = []
        self._pending_dec: List[bytes] = []
        self._flush_cb = flush_cb
        # objects this process owns (created here); owner keeps data alive
        # until cluster count drops to zero.
        self._owned: set = set()
        # called (outside the lock) when an object's local count reaches 0 —
        # the worker evicts its read-cache entry so value pins can release
        self._on_zero: Optional[Callable[[ObjectID], None]] = None

    def set_flush_cb(self, cb):
        self._flush_cb = cb

    def set_on_zero(self, cb: Callable[[ObjectID], None]):
        self._on_zero = cb

    def add_owned(self, oid: ObjectID):
        with self._lock:
            self._owned.add(oid)

    def remove_owned(self, oid: ObjectID):
        with self._lock:
            self._owned.discard(oid)

    def add_local_ref(self, oid: ObjectID) -> int:
        """Returns the new count (1 = this ref revived the object locally)."""
        with self._lock:
            n = self._counts.get(oid, 0)
            self._counts[oid] = n + 1
            if n == 0:
                self._pending_inc.append(oid.binary())
            return n + 1

    def remove_local_ref(self, oid: ObjectID):
        flush = None
        zero = False
        with self._lock:
            n = self._counts.get(oid, 0) - 1
            if n <= 0:
                self._counts.pop(oid, None)
                self._pending_dec.append(oid.binary())
                zero = True
                if len(self._pending_dec) >= 64:
                    flush = self._take_pending_locked()
            else:
                self._counts[oid] = n
        if zero and self._on_zero is not None:
            try:
                self._on_zero(oid)
            except Exception as e:
                # a failing eviction callback is a GC bug (leaked pins /
                # unevictable cache entries) — surface it, rate-limited,
                # instead of silently swallowing it
                from .ownership import warn_ratelimited

                warn_ratelimited(
                    "refcount-on-zero",
                    f"on-zero eviction callback failed for {oid}: {e!r}",
                )
        if flush and self._flush_cb:
            self._flush_cb(*flush)

    def _take_pending_locked(self):
        inc, dec = self._pending_inc, self._pending_dec
        self._pending_inc, self._pending_dec = [], []
        return inc, dec

    def flush(self):
        with self._lock:
            inc, dec = self._take_pending_locked()
        if (inc or dec) and self._flush_cb:
            self._flush_cb(inc, dec)

    def local_count(self, oid: ObjectID) -> int:
        with self._lock:
            return self._counts.get(oid, 0)

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._owned
