"""Worker process main: executes pushed tasks and hosts actors.

The analogue of the reference's worker-side TaskReceiver + scheduling queues
(src/ray/core_worker/transport/task_receiver.h): a unix-socket server receives
direct task pushes from drivers/other workers, executes them on an executor
(single thread by default; a pool for max_concurrency>1; the asyncio loop for
async-def actor methods), and replies with inline / shm / device-ref results.

Each worker process embeds a full Worker runtime so task code can itself call
remote()/get()/put() (nested tasks), sharing the process's asyncio loop.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import os
import sys
import threading
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from . import serialization
from .config import CAConfig, set_config
from .errors import TaskCancelledError, TaskError
from .ids import ActorID, ObjectID, TaskID
from .object_ref import ObjectRef
from .protocol import (
    TRACE_FIELD,
    MsgTemplate,
    Server,
    spawn_bg,
    write_frame,
    write_frame_body,
)

# completion replies on the fast path share one pre-encoded prefix; per reply
# only the request id and the results payload are packed.  Batched with
# whatever else the cork holds this tick, so a burst of completions travels
# worker→submitter as a few envelope frames (amortized acks).
_REPLY_TMPL = MsgTemplate({"ok": True}, ("i", "results"))
from .worker import Worker, _device_spec, _is_device_value, set_global_worker

# imported after .worker so the util package's own core imports resolve
# against a fully-initialized module
from ..util import logplane, tracing


class ActorContext:
    def __init__(self, actor_id: str, instance: Any, max_concurrency: int, incarnation: int):
        self.actor_id = actor_id
        self.instance = instance
        self.max_concurrency = max_concurrency
        self.incarnation = incarnation
        # concurrency groups (concurrency_group_manager.h): named thread
        # pools; methods are routed by their @method(concurrency_group=...)
        self.group_executors: Dict[str, Any] = {}
        # same bound for async methods, which run on the event loop rather
        # than a thread pool (fiber-concurrency analogue)
        self.group_semaphores: Dict[str, Any] = {}


class WorkerProcess:
    def __init__(self):
        self.session_dir = os.environ["CA_SESSION_DIR"]
        self.head_sock = os.environ["CA_HEAD_SOCK"]
        self.worker_id = os.environ["CA_WORKER_ID"]
        self.sock_path = os.environ["CA_WORKER_SOCK"]
        self.config = CAConfig.from_json(os.environ["CA_CONFIG_JSON"])
        set_config(self.config)
        self.node_id = os.environ.get("CA_NODE_ID", "n0")
        if self.config.log_capture:
            # log plane capture: stdout/stderr pass through to the raw .log
            # fd AND stamp each line (task/actor identity from the ambient
            # execution context) into nodes/<node_id>/<wid>.jsonl, which the
            # node's agent (or the head, on n0) tails and ships to drivers
            logplane.install_capture(
                self.session_dir, self.node_id, self.worker_id,
                max_bytes=self.config.log_rotate_bytes,
            )
        self.loop = asyncio.new_event_loop()
        if hasattr(asyncio, "eager_task_factory"):
            self.loop.set_task_factory(asyncio.eager_task_factory)
        self.worker: Optional[Worker] = None
        # dual-bind: unix for same-host peers, a TCP dual so remote (Ray-
        # Client-analogue) drivers can push tasks/actor calls directly
        specs = [self.sock_path]
        if not self.sock_path.startswith("tcp:"):
            host = getattr(self.config, "head_host", "127.0.0.1")
            specs.append(f"tcp:{host}:0")
        self.server = Server(specs, self._handle, fast_handler=self._fast_handle)
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ca-exec"
        )
        # compiled-DAG loops (__ca_exec__) live for the DAG's lifetime and
        # block on channel reads; hosting them on the actor's single dispatch
        # executor would freeze every other sync RPC to this actor for as
        # long as a DAG is compiled.  Lazy dedicated pool instead — one
        # thread per live loop, created on first compile.
        self._dag_executor: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self.actor: Optional[ActorContext] = None
        self._exiting = False
        # producer-side backpressure state per streaming task:
        # task_id -> {"acked": int, "event": threading.Event}
        self._streams: Dict[bytes, dict] = {}
        # task_id -> executing thread id (cancellation target)
        self._running_tasks: Dict[bytes, int] = {}
        # cancels that arrived BEFORE their task started executing (the push
        # may still be resolving args / fetching the function definition):
        # checked at _exec_sync entry.  FIFO-capped — a stale entry for a
        # task that already finished elsewhere must not pin memory forever.
        self._precancelled: "deque[bytes]" = deque(maxlen=1024)
        # every task id a cancel was ever requested for on this worker: lets
        # the execution wrapper distinguish a LEGITIMATE TaskCancelledError
        # from one that was async-delivered into the wrong task (the target
        # finished and the pool thread moved on in the race window).
        # FIFO-capped: eviction drops OLDEST marks first (a clear() could
        # wipe a mark whose async exception is still in flight)
        self._cancel_requested: "deque[bytes]" = deque(maxlen=1024)
        # async actor-method tasks in flight: task_id -> asyncio.Task
        # (cancellation for coroutines is task.cancel(), not async exc)
        self._async_running: Dict[bytes, Any] = {}
        # task_id -> rusage probe at execution start (metrics plane: the
        # terminal event carries CPU%/RSS/arena deltas derived from it)
        self._task_rusage0: Dict[bytes, dict] = {}

    # ----------------------------------------------------------- args/results
    def _resolve_arg(self, spec: dict) -> Any:
        if "v" in spec:
            from ..channel.device_transport import maybe_unpack

            if "t" in spec:
                # seed ack routing before unpack: a failed unpack raises out
                # of here, and the submitter's pin cleanup (sender liveness)
                # must not be confused by a misrouted late ack
                self.worker._note_transit_owners(spec)
            value = maybe_unpack(serialization.unpack(spec["v"]))
            if "t" in spec:
                # ack smuggled refs: our rehydrated handles are registered,
                # release the sender's transit pin (borrowing protocol)
                self.worker.transit_done(spec["t"], spec["roids"])
            return value
        if "shm" in spec:
            from .errors import StaleObjectError

            name = spec["shm"]
            if not self.worker.shm_store.is_local(name):
                # arg lives on another node: pull it over (runs on the
                # executor thread; the transfer itself rides the IO loop)
                name = self.worker.ensure_local_shm_blocking(
                    spec["oid"], name, spec.get("size", 0)
                )
            try:
                return self.worker.shm_store.get(name)
            except (StaleObjectError, FileNotFoundError):
                # the slice moved since the spec was minted (GC+recycle or
                # spill): re-resolve through the directory
                name = self.worker.ensure_local_shm_blocking(
                    spec["oid"], None, spec.get("size", 0)
                )
                return self.worker.shm_store.get(name)
        if "dev" in spec:
            oid = spec["dev"]
            if spec.get("owner") == self.sock_path and oid in self.worker.device_objects:
                return self.worker.device_objects[oid]
            reply = asyncio.run_coroutine_threadsafe(
                self.worker._fetch_remote_async(spec["owner"], oid), self.loop
            ).result(self.config.push_timeout_s)
            from ..channel.device_transport import maybe_unpack

            # a DeviceEnvelope lands shard-by-shard on this process's
            # devices with the producer's sharding reconstructed
            return maybe_unpack(serialization.unpack(reply["packed"]))
        raise ValueError(f"bad arg spec keys: {list(spec)}")

    def _resolve_args(self, specs, kwspecs):
        args = [self._resolve_arg(s) for s in specs]
        kwargs = {k: self._resolve_arg(s) for k, s in (kwspecs or {}).items()}
        return args, kwargs

    def _package_result(self, oid_bytes: bytes, value: Any, owner: str) -> dict:
        if _is_device_value(value):
            self.worker.device_objects[oid_bytes] = value
            return {"dev": oid_bytes, "owner": self.sock_path, "spec": _device_spec(value)}
        with serialization.ref_capture() as nested:
            data, buffers = serialization.serialize(value)
        raws = [b.raw() for b in buffers]
        total = len(data) + sum(len(r) for r in raws)
        if total < self.config.inline_object_max_bytes:
            if nested:
                # returned value smuggles ObjectRefs: pin them under a
                # transit token until the submitter's handles register
                token = self.worker.transit_pin(nested)
                return {
                    "v": serialization.pack(value), "t": token, "roids": nested,
                    "rown": self.worker.transit_owners(nested),
                }
            return {"v": serialization.pack(value)}
        oid = ObjectID(oid_bytes)
        shm_name, size = self.worker.shm_store.create_and_pack(oid, data, raws)
        if nested:
            self.worker._promote_nested(nested)
        # ownership of the returned object belongs to the *submitter*
        # (reference ownership model): it decides when the segment dies.
        self.worker._notify_threadsafe(
            "obj_created", oid=oid_bytes, shm_name=shm_name, size=size, owner=owner
        )
        out = {"shm": shm_name, "size": size}
        if nested:
            # refs inside the stored value live as long as it does: edges
            # register at each nested ref's lifetime authority under the
            # SUBMITTER's edge id, and the pairs travel with the result so
            # the submitter's ledger releases them when the container dies
            pairs = self.worker.result_contains_pairs(oid_bytes, nested, owner)
            if pairs is None:
                self.worker._notify_threadsafe(
                    "obj_contains", oid=oid_bytes, refs=nested
                )
            else:
                out["contains"] = pairs
        return out

    def _package_results(
        self, task_id: bytes, num_returns: int, value: Any, owner: str
    ) -> List[dict]:
        tid = TaskID(task_id)
        if num_returns == 1:
            values = [value]
        else:
            if not isinstance(value, (tuple, list)) or len(value) != num_returns:
                raise TaskError(
                    f"task declared num_returns={num_returns} but returned {type(value).__name__}"
                )
            values = list(value)
        return [
            self._package_result(ObjectID.for_return(tid, i).binary(), v, owner)
            for i, v in enumerate(values)
        ]

    def _error_results(self, num_returns: int, exc: BaseException) -> List[dict]:
        import pickle

        from .errors import CAError

        # CAError subclasses keep their type across the wire: the submitter
        # reacts to them (e.g. ObjectLostError triggers lineage
        # reconstruction); everything else becomes a TaskError with traceback
        if not isinstance(exc, CAError):
            tb = traceback.format_exc()
            # the last lines this worker printed travel with the error: the
            # caller sees what the task said right before it died without a
            # separate `ca logs` round-trip
            tail = logplane.recent_lines(20)
            if tail:
                tb += (
                    "\n--- last captured worker output ---\n"
                    + "\n".join(tail)
                    + "\n"
                )
            exc = TaskError(repr(exc), tb)
        blob = pickle.dumps(exc)
        return [{"e": blob} for _ in range(num_returns)]

    # --------------------------------------------------------------- execute
    def _exec_sync(self, fn, msg, task_id: bytes, actor_id: Optional[str]) -> List[dict]:
        """Arg resolution + user code + result packaging in ONE executor job
        (per-caller actor-call ordering preserved end-to-end, one thread
        hop).  TaskCancelledError delivered here when the task was never
        actually cancel-requested means the async exception landed in the
        wrong task (cancel raced the pool thread finishing its target and
        starting us): re-run once — same at-least-once semantics as a
        worker-death retry."""
        tr = msg.get(TRACE_FIELD)
        token = None
        # log-plane attribution for everything this task prints (always on,
        # unlike the trace context which only rides traced submissions)
        ltok = logplane.push_context(
            task=task_id.hex(),
            actor=actor_id,
            name=msg.get("method") or getattr(fn, "__name__", "task"),
        )
        if tr is not None:
            # install the submitter's trace context as ambient for this
            # executor thread: nested remote() calls and tracing.span()
            # blocks inside user code chain into the same trace
            token = tracing.push_execution(tr)
            self._record_running(
                task_id,
                msg.get("method") or getattr(fn, "__name__", "task"),
                "actor_task" if actor_id else "task",
                tr,
            )
        try:
            return self._exec_sync_inner(fn, msg, task_id, actor_id)
        except TaskCancelledError:
            if task_id in self._cancel_requested:
                try:
                    self._cancel_requested.remove(task_id)
                except ValueError:
                    pass
                raise
            if msg.get("retriable", True):
                return self._exec_sync_inner(fn, msg, task_id, actor_id)
            raise TaskError(
                "task interrupted by a cancellation aimed at another task "
                "and declared non-retriable (max_retries=0)"
            )
        except BaseException as e:
            # CA_POST_MORTEM=1 (reference RAY_DEBUG_POST_MORTEM role): serve
            # a remote pdb on the failure frame before the error propagates.
            # Runs on the executor thread, so the worker's IO loop (and its
            # health checks) stay live while a human is attached.
            if os.environ.get("CA_POST_MORTEM") == "1" and not isinstance(
                e, (SystemExit, KeyboardInterrupt)
            ):
                try:
                    from ..util.rpdb import post_mortem

                    post_mortem(e)
                except Exception:
                    pass
            raise
        finally:
            logplane.pop_context(ltok)
            if token is not None:
                tracing.pop_execution(token)
            if self._cancel_requested or self._precancelled:
                # backstop for the delivery race: retract any async
                # exception still pending on THIS thread before it returns
                # to the pool (an escape there kills the executor thread)
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(threading.get_ident()), None
                )

    def _exec_sync_inner(self, fn, msg, task_id: bytes, actor_id: Optional[str]) -> List[dict]:
        args, kwargs = self._resolve_args(msg["args"], msg.get("kwargs"))
        w = self.worker
        w.current_task_id = TaskID(task_id)
        if actor_id:
            w.current_actor_id = ActorID.from_hex(actor_id)
        ctx = None
        # cancellation point: ca.cancel() raises TaskCancelledError in this
        # thread via PyThreadState_SetAsyncExc (task_canceller.h role); a
        # cancel that raced ahead of execution start fires here instead
        if task_id in self._precancelled:
            try:
                self._precancelled.remove(task_id)
            except ValueError:
                pass
            w.current_task_id = None
            raise TaskCancelledError("task was cancelled")
        self._running_tasks[task_id] = threading.get_ident()
        try:
            if msg.get("runtime_env"):
                from .runtime_env import RuntimeEnvContext

                ctx = RuntimeEnvContext(msg["runtime_env"], w)
                ctx.apply()  # inside try: a partial apply must still restore
            value = fn(*args, **kwargs)
        finally:
            self._running_tasks.pop(task_id, None)
            w.current_task_id = None
            if ctx is not None:
                ctx.restore()  # pool workers are reused
        return self._package_results(
            task_id, msg.get("num_returns", 1), value, msg.get("owner", "")
        )

    def _h_cancel_task(self, msg):
        """Owner-requested cancellation of a task running HERE.  Non-force:
        raise TaskCancelledError inside the executing thread (CPython async
        exception — lands at the next bytecode boundary, so C-level blocking
        calls are not interruptible; that is what force is for).  Force:
        hard-exit the process; the owner maps the resulting worker death to
        TaskCancelledError instead of a retry."""
        task_id = msg["task_id"]
        self._cancel_requested.append(task_id)
        atask = self._async_running.get(task_id)
        if atask is not None:
            # coroutine actor method: asyncio cancellation is exact (no
            # async-exc race).  force cannot rely on cooperation (the method
            # may suppress CancelledError): hard-exit if it is still running
            # after a grace period
            atask.cancel()
            if msg.get("force"):
                def _enforce():
                    if task_id in self._async_running:
                        os._exit(1)

                self.loop.call_later(1.0, _enforce)
            return
        if msg.get("force"):
            if task_id in self._running_tasks:
                os._exit(1)
            # not running yet: the pre-cancel check at _exec_sync entry stops
            # it before user code, which force semantics subsume
            self._precancelled.append(task_id)
            return
        tid = self._running_tasks.get(task_id)
        if tid is None:
            # the push may still be resolving args / fetching the function:
            # remember the cancel so execution start aborts (finished tasks
            # leave a harmless FIFO-capped entry; the owner no-ops those)
            self._precancelled.append(task_id)
            return
        import ctypes

        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), ctypes.py_object(TaskCancelledError)
        )
        if self._running_tasks.get(task_id) != tid:
            # the target finished between lookup and delivery: try to
            # retract before the pending exception fires in whatever that
            # thread runs next (best-effort; the _exec_sync wrapper's
            # trailing clear is the backstop)
            ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)

    def _arena_bytes(self) -> Optional[int]:
        """Live bytes in this worker's shm arenas (metrics-plane resource
        attribution on terminal task events); None when unavailable."""
        try:
            return sum(
                a.size - sum(sz for _, sz in a.free)
                for a in self.worker.shm_store._arenas.values()
            )
        except Exception:
            return None

    def _record_event(
        self, task_id: bytes, name: str, kind: str, t0: float, ok: bool,
        trace: Optional[dict] = None,
    ):
        import time as _time

        extra = {}
        p0 = self._task_rusage0.pop(task_id, None)
        if p0 is not None:
            # CPU%/RSS/arena sample pair bracketing the task: rides the
            # task-event path into timeline()/`ca summary` (process-wide
            # numbers — concurrent tasks on one worker share them)
            from ..util import profiler

            try:
                extra["rusage"] = profiler.rusage_delta(
                    t0, p0, self._arena_bytes()
                )
            except Exception:
                pass
        tracing.record_task_event(
            task_id.hex(), name, kind,
            "FINISHED" if ok else "FAILED",
            trace=trace,
            worker_id=self.worker_id,
            node_id=self.worker.node_id if self.worker is not None else None,
            actor_id=self.actor.actor_id if self.actor else None,
            start=t0,
            end=_time.time(),
            **extra,
        )

    def _record_running(self, task_id: bytes, name: Optional[str], kind: str, tr: dict):
        """Lifecycle RUNNING phase (only for traced tasks: `tr` came over
        the wire, so tracing was enabled at the submitter)."""
        tracing.record_task_event(
            task_id.hex(), name, kind, "RUNNING",
            trace=tr,
            worker_id=self.worker_id,
            node_id=self.worker.node_id if self.worker is not None else None,
        )

    async def _execute(self, msg, is_actor_call: bool) -> List[dict]:
        import time as _time

        num_returns = msg.get("num_returns", 1)
        task_id = msg.get("task_id") or os.urandom(16)
        t0 = _time.time()
        from ..util import profiler as _profiler

        self._task_rusage0[task_id] = _profiler.rusage_probe()
        tr = msg.get(TRACE_FIELD)
        ev_name = msg.get("method") if is_actor_call else None
        try:
            if is_actor_call:
                if self.actor is None or self.actor.actor_id != msg["actor_id"]:
                    raise TaskError(f"actor {msg.get('actor_id')} not hosted here")
                if msg["method"] == "__ca_exec__":
                    # built-in escape hatch: first arg is a function applied to
                    # the actor instance (used by compiled DAG loops; analogue
                    # of the reference's __ray_call__)
                    inst = self.actor.instance

                    def method(fn, *a, **kw):
                        return fn(inst, *a, **kw)

                else:
                    method = getattr(self.actor.instance, msg["method"])
                if asyncio.iscoroutinefunction(method):
                    args, kwargs = await self.loop.run_in_executor(
                        None, self._resolve_args, msg["args"], msg.get("kwargs")
                    )
                    sem = self._semaphore_for(method)
                    async with sem if sem is not None else contextlib.nullcontext():
                        # tracked so ca.cancel() can asyncio-cancel it.  The
                        # ambient trace context is installed around task
                        # creation only: coroutines snapshot it then, so the
                        # method body (and anything it submits) is traced
                        # without leaking context onto the shared loop
                        token = None
                        # the coroutine snapshots the ambient context at task
                        # creation: log attribution and (when traced) trace
                        # context both ride into the method body
                        ltok = logplane.push_context(
                            task=task_id.hex(), actor=msg["actor_id"],
                            name=msg["method"],
                        )
                        if tr is not None:
                            token = tracing.push_execution(tr)
                            self._record_running(task_id, ev_name, "actor_task", tr)
                        try:
                            coro_task = asyncio.ensure_future(method(*args, **kwargs))
                        finally:
                            logplane.pop_context(ltok)
                            if token is not None:
                                tracing.pop_execution(token)
                        self._async_running[task_id] = coro_task
                        if task_id in self._precancelled:
                            # cancel landed while args resolved / semaphore
                            # queued: apply it now instead of dropping it
                            try:
                                self._precancelled.remove(task_id)
                            except ValueError:
                                pass
                            coro_task.cancel()
                        try:
                            value = await coro_task
                        # the CancelledError is coro_task's (ca.cancel /
                        # precancel landed on the CHILD task), not this
                        # dispatch task's: converting it to the cancel
                        # protocol's reply is the designed behavior
                        except asyncio.CancelledError:  # ca-lint: ignore[async-swallowed-cancel]
                            raise TaskCancelledError("task was cancelled")
                        finally:
                            self._async_running.pop(task_id, None)
                    out = await self.loop.run_in_executor(
                        None,
                        self._package_results,
                        task_id,
                        num_returns,
                        value,
                        msg.get("owner", ""),
                    )
                    self._record_event(task_id, ev_name, "actor_task", t0, True, trace=tr)
                    return out
                sem = self._semaphore_for(method)
                async with sem if sem is not None else contextlib.nullcontext():
                    ex = (
                        self._dag_pool()
                        if msg["method"] == "__ca_exec__"
                        else self._executor_for(method)
                    )
                    out = await self.loop.run_in_executor(
                        ex,
                        self._exec_sync, method, msg, task_id, msg["actor_id"],
                    )
                self._record_event(task_id, ev_name, "actor_task", t0, True, trace=tr)
                return out
            fn = self.worker.fn_manager.get(msg["fn_id"])
            if fn is None:
                if msg.get("fn_blob") is not None:
                    # definition inlined by a submitter that saw the head
                    # down — no head dependency on this push at all
                    fn = self.worker.fn_manager.load(msg["fn_id"], msg["fn_blob"])
                else:
                    fn = await self._fetch_function(msg["fn_id"])
            ev_name = getattr(fn, "__name__", "task")
            out = await self.loop.run_in_executor(
                self.executor, self._exec_sync, fn, msg, task_id, None
            )
            self._record_event(task_id, ev_name, "task", t0, True, trace=tr)
            return out
        except SystemExit:
            self._exiting = True
            self._task_rusage0.pop(task_id, None)
            if self.actor is not None:
                try:
                    self.worker.head.notify("actor_exited", actor_id=self.actor.actor_id)
                except Exception:
                    pass
            return self._error_results(num_returns, TaskError("actor exited via exit_actor()"))
        except asyncio.CancelledError:
            raise  # worker shutdown: the peer sees the drop, not a "result"
        except BaseException as e:
            self._record_event(
                task_id,
                ev_name or "task",
                "actor_task" if is_actor_call else "task",
                t0,
                False,
                trace=tr,
            )
            return self._error_results(num_returns, e)

    # ------------------------------------------------------------- streaming
    def _exec_streaming(self, fn, msg, writer, actor_id: Optional[str]):
        """Run a generator task on the executor thread, streaming each yield
        to the submitter with bounded unconsumed items (generator_waiter.h
        backpressure).  Returns the frames-level terminal reply fields."""
        import time as _time

        task_id = msg.get("task_id") or os.urandom(16)
        owner = msg.get("owner", "")
        limit = self.config.streaming_backpressure
        stream = {"acked": 0, "event": threading.Event()}
        self._streams[task_id] = stream
        # generator tasks are cancellable too (async exc lands between
        # yields; force kills the process like any running task)
        self._running_tasks[task_id] = threading.get_ident()
        t0 = _time.time()
        from ..util import profiler as _profiler

        self._task_rusage0[task_id] = _profiler.rusage_probe()
        idx = 0
        tr = msg.get(TRACE_FIELD)
        token = None
        ltok = logplane.push_context(
            task=task_id.hex(), actor=actor_id,
            name=msg.get("method") or getattr(fn, "__name__", "stream"),
        )
        if tr is not None:
            token = tracing.push_execution(tr)
            self._record_running(
                task_id, getattr(fn, "__name__", "stream"), "task", tr
            )
        try:
            args, kwargs = self._resolve_args(msg["args"], msg.get("kwargs"))
            w = self.worker
            w.current_task_id = TaskID(task_id)
            try:
                gen = fn(*args, **kwargs)
                for item in gen:
                    # backpressure: wait for the consumer before running ahead
                    while idx - stream["acked"] >= limit:
                        stream["event"].clear()
                        if idx - stream["acked"] < limit:
                            break  # ack landed between check and clear
                        if not stream["event"].wait(self.config.push_timeout_s):
                            raise TaskError(
                                "streaming consumer stalled past the timeout"
                            )
                    res = self._package_result(
                        ObjectID.for_return(TaskID(task_id), idx).binary(), item, owner
                    )

                    def _push(res=res, i=idx):
                        write_frame(
                            writer,
                            {"m": "stream_item", "task_id": task_id, "idx": i, "res": res},
                        )

                    self.loop.call_soon_threadsafe(_push)
                    idx += 1
            finally:
                w.current_task_id = None
            self._record_event(
                task_id, getattr(fn, "__name__", "stream"), "task", t0, True,
                trace=tr,
            )
            return {"results": [], "stream_end": True, "count": idx}
        except TaskCancelledError as e:
            self._record_event(
                task_id, getattr(fn, "__name__", "stream"), "task", t0, False,
                trace=tr,
            )
            if task_id not in self._cancel_requested:
                # stray delivery (cancel aimed at a task this thread just
                # finished): a stream cannot re-run mid-way, so surface an
                # explicit error rather than a false "cancelled"
                e = TaskError(
                    "stream interrupted by a cancellation aimed at another task"
                )
            else:
                try:
                    self._cancel_requested.remove(task_id)
                except ValueError:
                    pass
            err = self._error_results(1, e)[0]["e"]
            return {"results": [], "stream_end": True, "count": idx, "stream_error": err}
        except BaseException as e:
            self._record_event(
                task_id, getattr(fn, "__name__", "stream"), "task", t0, False,
                trace=tr,
            )
            err = self._error_results(1, e)[0]["e"]
            return {"results": [], "stream_end": True, "count": idx, "stream_error": err}
        finally:
            logplane.pop_context(ltok)
            if token is not None:
                tracing.pop_execution(token)
            self._streams.pop(task_id, None)
            self._running_tasks.pop(task_id, None)
            if self._cancel_requested or self._precancelled:
                # same backstop as _exec_sync: retract a pending async
                # exception before this pool thread is reused
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(threading.get_ident()), None
                )

    def _h_stream_ack(self, msg):
        stream = self._streams.get(msg["task_id"])
        if stream is not None:
            stream["acked"] = max(stream["acked"], msg["consumed"])
            stream["event"].set()

    # --------------------------------------------------------------- handlers
    def _fast_handle(self, state, msg, writer) -> bool:
        """Synchronous hot path run directly in the server read loop: execute
        sync tasks/actor calls by handing the executor a job whose done-
        callback writes the reply — no per-frame asyncio Task, no coroutine.
        Returns False to fall back to the general async handler (async
        methods, uncached functions, control RPCs)."""
        m = msg.get("m")
        if msg.get("num_returns") == "streaming":
            return False  # generator tasks take the streaming path
        if m == "actor_call":
            ctx = self.actor
            if ctx is None or ctx.actor_id != msg.get("actor_id"):
                return False
            name = msg.get("method")
            if name == "__ca_exec__":
                return False
            fn = getattr(ctx.instance, name, None)
            if fn is not None and self._semaphore_for(fn) is not None:
                # grouped methods take the slow path so the group semaphore
                # is the single width gate across sync/async/streaming
                return False
            if fn is None or asyncio.iscoroutinefunction(fn):
                return False
            self._submit_fast(fn, msg, writer, msg["actor_id"], "actor_task", name)
            return True
        if m == "push_task":
            fn = self.worker.fn_manager.get(msg["fn_id"])
            if fn is None:
                return False  # definition needs a head fetch: slow path
            self._submit_fast(
                fn, msg, writer, None, "task", getattr(fn, "__name__", "task")
            )
            return True
        if m == "stream_ack":
            self._h_stream_ack(msg)
            return True
        return False

    def _executor_for(self, fn):
        """Route a method to its concurrency group's thread pool (default:
        the actor's main executor)."""
        if self.actor is not None and self.actor.group_executors:
            group = getattr(fn, "__ca_method_options__", {}).get("concurrency_group")
            if group is not None:
                ex = self.actor.group_executors.get(group)
                if ex is not None:
                    return ex
        return self.executor

    def _dag_pool(self):
        """Dedicated executor for compiled-DAG loops, pinned off the RPC
        dispatch path: the cap bounds runaway compiles, not steady state
        (one thread per concurrently-compiled DAG on this actor)."""
        if self._dag_executor is None:
            self._dag_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="ca-dag-loop"
            )
        return self._dag_executor

    def _semaphore_for(self, fn):
        """Concurrency-group bound for async methods: thread pools can't cap
        coroutines, so declared groups get an asyncio.Semaphore of the same
        width. Ungrouped async methods stay unbounded (interleaving is the
        point of an async actor)."""
        if self.actor is None or not self.actor.group_semaphores:
            return None
        group = getattr(fn, "__ca_method_options__", {}).get("concurrency_group")
        if group is None:
            return None
        return self.actor.group_semaphores.get(group)

    def _submit_fast(self, fn, msg, writer, actor_id, kind, ev_name):
        import time as _time

        rid = msg.get("i")
        task_id = msg.get("task_id") or os.urandom(16)
        num_returns = msg.get("num_returns", 1)
        t0 = _time.time()

        def job():
            ok = True
            exited_actor = None
            try:
                results = self._exec_sync(fn, msg, task_id, actor_id)
            except SystemExit:
                self._exiting = True
                results = self._error_results(
                    num_returns, TaskError("actor exited via exit_actor()")
                )
                if self.actor is not None:
                    exited_actor = self.actor.actor_id
            except BaseException as e:
                ok = False
                results = self._error_results(num_returns, e)

            def finish():
                # notify/write only from the loop thread (the cork needs a
                # running loop); actor_exited must precede the process death
                # so the head records a graceful exit, not a crash-to-restart
                if exited_actor is not None:
                    try:
                        self.worker.head.notify("actor_exited", actor_id=exited_actor)
                    except Exception:
                        pass
                if rid is not None:
                    write_frame_body(writer, _REPLY_TMPL.render(rid, results))
                self._record_event(task_id, ev_name, kind, t0, ok, trace=msg.get(TRACE_FIELD))
                if self._exiting:
                    spawn_bg(self._graceful_exit())

            self.loop.call_soon_threadsafe(finish)

        self._executor_for(fn).submit(job)

    async def _handle(self, state, msg, reply, reply_err):
        m = msg["m"]
        if msg.get("num_returns") == "streaming" and m in ("push_task", "actor_call"):
            fn = await self._resolve_callable(msg, is_actor_call=(m == "actor_call"))
            if isinstance(fn, dict):  # resolution error -> terminal reply
                reply(**fn)
                return
            sem = self._semaphore_for(fn)
            async with sem if sem is not None else contextlib.nullcontext():
                out = await self.loop.run_in_executor(
                    self._executor_for(fn), self._exec_streaming, fn, msg,
                    state["writer"], msg.get("actor_id"),
                )
            reply(**out)
        elif m == "push_task":
            results = await self._execute(msg, is_actor_call=False)
            reply(results=results)
        elif m == "actor_call":
            results = await self._execute(msg, is_actor_call=True)
            reply(results=results)
            if self._exiting:
                await self._graceful_exit()
        elif m == "stream_ack":
            self._h_stream_ack(msg)
        elif m == "spawn_actor":
            try:
                await self._spawn_actor(msg)
                reply()
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                reply_err(TaskError(repr(e), traceback.format_exc()))
        elif m == "fetch_object":
            try:
                reply(packed=await self._fetch_object(msg["oid"]))
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                reply_err(e)
        elif m == "owner_locate":
            # ownership-based object directory read path: this process is
            # authoritative for objects it owns (see Worker.owner_locate_async)
            reply(**await self.worker.owner_locate_async(msg["oid"]))
        elif m == "owner_refs":
            # ownership plane write path: a borrower settling inc/dec
            # against this process's OwnerLedger (worker<->worker, no head)
            self.worker.serve_owner_refs(
                msg.get("inc"), msg.get("dec"),
                msg.get("as_id") or state.get("client_id", "?"),
                bool(msg.get("ttl")),
            )
            reply()
        elif m == "owner_transit_done":
            self.worker.serve_owner_transit_done(
                msg["token"], msg.get("oids"), msg.get("cid", "?"),
                msg.get("register", True),
            )
            reply()
        elif m == "owner_pin":
            reply(**self.worker.serve_owner_pin(msg["oid"], msg["as_id"]))
        elif m == "coll_push":
            # p2p collective transport: land the chunk in the rank mailbox
            # (meta rides along for quantized payloads — scales, block size)
            self.worker.coll_deliver(
                msg["group"], msg["key"], msg["src"],
                msg["data"], msg["shape"], msg["dtype"],
                msg.get("meta"),
            )
            reply()
        elif m == "profile":
            # metrics plane: in-process stack sampler (`ca profile`).  Runs
            # on the loop's DEFAULT executor, never the task executor — the
            # busy task being profiled is occupying that one, and the whole
            # point is to observe it
            from ..util import profiler

            res = await self.loop.run_in_executor(
                None, profiler.sample_stacks,
                float(msg.get("duration", 2.0)), float(msg.get("hz", 100.0)),
            )
            reply(
                folded=profiler.render_folded(res["folded"]),
                speedscope=profiler.speedscope_json(
                    res["folded"], f"worker {self.worker_id}", res["hz"]
                ),
                samples=res["samples"],
                duration_s=res["duration_s"],
            )
        # operator liveness probe (BlockingClient / manual socket debugging):
        # ca-lint: ignore[rpc-dead-handler]
        elif m == "ping":
            reply(worker_id=self.worker_id, actor=self.actor.actor_id if self.actor else None)
        elif m == "cancel":
            self._h_cancel_task(msg)
            reply()
        else:
            reply_err(ValueError(f"unknown worker method {m}"))

    async def _fetch_function(self, fn_id):
        """Fetch + load a function blob from the head, riding through a head
        restart: the task asking for it was legitimately pushed (lease-plane
        grants keep flowing while the control plane is down), so a transient
        head outage must not turn it into a spurious TaskError.  The
        housekeeping loop redials; this retries until the push timeout."""
        deadline = self.loop.time() + self.worker.config.push_timeout_s
        while True:
            # a concurrent push may have inlined the definition (submitters
            # ship fn_blob once per connection during head outages) — the
            # local cache beats another head round-trip
            fn = self.worker.fn_manager.get(fn_id)
            if fn is not None:
                return fn
            try:
                reply = await self.worker.head.call("get_function", fn_id=fn_id)
                break
            except ConnectionError:
                if self.loop.time() > deadline:
                    raise
                await asyncio.sleep(0.5)
        return self.worker.fn_manager.load(fn_id, reply["blob"])

    async def _resolve_callable(self, msg, is_actor_call: bool):
        """Resolve the task function / actor method for the streaming path.
        Returns the callable, or a terminal-reply dict on failure."""
        try:
            if is_actor_call:
                if self.actor is None or self.actor.actor_id != msg["actor_id"]:
                    raise TaskError(f"actor {msg.get('actor_id')} not hosted here")
                return getattr(self.actor.instance, msg["method"])
            fn = self.worker.fn_manager.get(msg["fn_id"])
            if fn is None:
                if msg.get("fn_blob") is not None:
                    fn = self.worker.fn_manager.load(msg["fn_id"], msg["fn_blob"])
                else:
                    fn = await self._fetch_function(msg["fn_id"])
            return fn
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            err = self._error_results(1, e)[0]["e"]
            return {"results": [], "stream_end": True, "count": 0, "stream_error": err}

    async def _spawn_actor(self, msg):
        cls = self.worker.fn_manager.get(msg["fn_id"])
        if cls is None:
            reply = await self.worker.head.call("get_function", fn_id=msg["fn_id"])
            cls = self.worker.fn_manager.load(msg["fn_id"], reply["blob"])
        specs, kwspecs = serialization.unpack(msg["init_spec"])
        max_concurrency = msg.get("max_concurrency", 1)
        if max_concurrency > 1:
            self.executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_concurrency, thread_name_prefix="ca-exec"
            )
        group_executors = {
            name: concurrent.futures.ThreadPoolExecutor(
                max_workers=max(1, int(n)), thread_name_prefix=f"ca-cg-{name}"
            )
            for name, n in (msg.get("concurrency_groups") or {}).items()
        }

        def _make():
            if msg.get("runtime_env"):
                # dedicated actor process: the env applies for its lifetime
                from .runtime_env import RuntimeEnvContext

                RuntimeEnvContext(msg["runtime_env"], self.worker).apply()
            args, kwargs = self._resolve_args(specs, kwspecs)
            return cls(*args, **kwargs)

        instance = await self.loop.run_in_executor(self.executor, _make)
        self.actor = ActorContext(
            msg["actor_id"], instance, max_concurrency, msg.get("incarnation", 0)
        )
        self.actor.group_executors = group_executors
        self.actor.group_semaphores = {
            name: asyncio.Semaphore(max(1, int(n)))
            for name, n in (msg.get("concurrency_groups") or {}).items()
        }
        self.worker.current_actor_id = ActorID.from_hex(msg["actor_id"])

    async def _fetch_object(self, oid: bytes) -> bytes:
        value = self.worker.device_objects.get(oid)
        if value is None:
            e = self.worker.memory_store.get_entry(ObjectID(oid))
            if e is None or e.state == "pending":
                raise KeyError(f"object {oid.hex()} not found on this worker")
            # resolve on an executor thread, NOT the IO loop: the full
            # recovery path (confirmed pins, relocation after spill,
            # reconstruction) drives RPCs through the loop and would
            # deadlock/degrade if entered from it
            value = await self.loop.run_in_executor(
                None, self.worker._resolve_entry, ObjectRef(ObjectID(oid))
            )
        if _is_device_value(value):
            # device-native: ship per-shard buffer borrows + sharding
            # metadata, not a device_get'd host copy (channel/device_transport).
            # Packing does per-shard D2H DMAs — executor thread, not the IO
            # loop, or a multi-GB transfer stalls heartbeats and RPC serving
            from ..channel.device_transport import pack_device_value

            return await self.loop.run_in_executor(
                None, lambda: serialization.pack(pack_device_value(value))
            )
        return await self.loop.run_in_executor(None, serialization.pack, value)

    async def _graceful_exit(self):
        await asyncio.sleep(0.05)  # let replies flush
        os._exit(0)

    async def _heartbeat_loop(self):
        period = self.config.health_check_period_s / 2
        while True:
            await asyncio.sleep(min(period, 1.0))
            try:
                self.worker.head.notify("heartbeat", client_id=self.worker_id)
            except Exception:
                pass

    # ------------------------------------------------------------------ main
    async def _amain(self):
        # start serving first: with "tcp:host:0" the advertised address is
        # only known after bind (agent-spawned workers on other nodes)
        await self.server.start()
        self.sock_path = self.server.bound_addrs[0]
        addr_tcp = next(
            (a for a in self.server.bound_addrs if a.startswith("tcp:")), None
        )
        self.worker = Worker(
            mode="worker",
            session_dir=self.session_dir,
            head_sock=self.head_sock,
            config=self.config,
            client_id=self.worker_id,
            loop=self.loop,
            serve_addr=self.sock_path,
            serve_addr_tcp=addr_tcp,
        )
        set_global_worker(self.worker)
        # fence hook: a death verdict (FencedError / refused re-register /
        # `fenced` push) cancels running zombie tasks IMMEDIATELY — their
        # side effects must not complete — instead of waiting a watch tick
        self.worker._on_fenced_cb = self._fenced_now
        await self.worker.connect_async()
        spawn_bg(self._heartbeat_loop())
        spawn_bg(self._watch_head())
        # park forever; the head kills us at job teardown
        await asyncio.Event().wait()

    def _fenced_now(self):
        """Death-verdict entry point; may fire from a user thread (a task's
        own head_call raising FencedError) — hop to the loop."""
        try:
            self.loop.call_soon_threadsafe(self._fenced_on_loop)
        except RuntimeError:
            os._exit(1)

    def _fenced_on_loop(self):
        """Death verdict landed: this worker's node incarnation was declared
        dead (partition heal discovery).  Cancel every RUNNING task — the
        head already resubmitted them elsewhere, so letting them finish
        would commit duplicate side effects — then exit.  The cancellation
        is the difference between "zombie completed, then died" and "zombie
        died mid-flight": only the latter is at-most-once."""
        import ctypes

        for task_id in list(self._async_running):
            t = self._async_running.get(task_id)
            if t is not None:
                t.cancel()
        for task_id, tid in list(self._running_tasks.items()):
            self._cancel_requested.append(task_id)
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(TaskCancelledError)
            )
        # brief grace for the cancellations to unwind, then hard exit (the
        # agent's fence reset SIGKILLs us anyway if we linger)
        self.loop.call_later(0.25, os._exit, 1)

    async def _watch_head(self):
        """Watch the head connection.  A dead head gets a reconnect grace
        window (the Worker housekeeping loop redials; a restarted head
        re-adopts us from its snapshot).  Exit when (a) the head explicitly
        fenced us — it declared this worker dead, a stale lease must not keep
        acting — or (b) the grace expires with no head (orphan reaping)."""
        grace = self.config.health_check_period_s * self.config.health_check_failure_threshold + 10.0
        down_since = None
        while True:
            await asyncio.sleep(0.5)
            if self.worker._head_fenced:
                os._exit(1)
            if self.worker.head is None or self.worker.head.closed:
                if down_since is None:
                    down_since = asyncio.get_running_loop().time()
                elif asyncio.get_running_loop().time() - down_since > grace:
                    os._exit(1)
            else:
                down_since = None

    def main(self):
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_until_complete(self._amain())
        except (KeyboardInterrupt, SystemExit):
            pass


def main():
    # debugging facility: SIGUSR1 dumps all thread stacks to the worker log
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    WorkerProcess().main()


if __name__ == "__main__":
    main()
