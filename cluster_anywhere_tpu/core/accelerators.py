"""TPU accelerator manager: chip/topology detection feeding the resource model.

Reference parity: ``python/ray/_private/accelerators/tpu.py:70``
(TPUAcceleratorManager) and ``python/ray/util/accelerators/tpu.py`` (pod
helpers).  Detection is env/device-file driven and never calls a metadata
service (zero-egress environments) — a GKE/GCE-style deployment sets the
standard ``TPU_*`` variables, a bare libtpu host exposes ``/dev/accel*``, and
the axon dev tunnel advertises ``PALLAS_AXON_TPU_GEN``.

Detected topology surfaces as schedulable resources at ``init``:
  TPU                  chips on this host (the reference's TPU resource)
  TPU-<GEN>            accelerator-type marker, e.g. TPU-V5E (1 per chip)
  TPU-<pod_type>-head  exactly one, on worker 0 of a pod slice — lets a
                       driver pin one task per pod for SPMD launch
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional

RESOURCE_NAME = "TPU"
VALID_CHIP_REQUESTS = (1, 2, 4, 8)  # whole-host or sub-host chip groups

VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
NOSET_VISIBLE_CHIPS_ENV = "CA_EXPERIMENTAL_NOSET_TPU_VISIBLE_CHIPS"
ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5e-16" (pod type)
CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"  # e.g. "2,2,1"
HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
WORKER_ID_ENV = "TPU_WORKER_ID"
POD_NAME_ENV = "TPU_NAME"
_AXON_GEN_ENV = "PALLAS_AXON_TPU_GEN"  # dev tunnel: one chip of this gen


def visible_chip_ids() -> Optional[list]:
    """Chip ids this process may use, or None when unrestricted
    (get_current_process_visible_accelerator_ids analogue)."""
    v = os.environ.get(VISIBLE_CHIPS_ENV)
    if v is None or v == "":
        return None
    return [s for s in v.split(",") if s != ""]


def num_tpu_chips() -> int:
    """TPU chips on this host.  Priority: visible-chips restriction, explicit
    host-bounds env, /dev/accel* device files, axon dev-tunnel marker."""
    vis = visible_chip_ids()
    if vis is not None:
        return len(vis)
    bounds = os.environ.get(CHIPS_PER_HOST_BOUNDS_ENV)
    if bounds:
        try:
            n = 1
            for part in bounds.split(","):
                n *= int(part)
            return n
        except ValueError:
            pass
    dev = glob.glob("/dev/accel*")
    if dev:
        return len(dev)
    if os.environ.get(_AXON_GEN_ENV):
        return 1
    return 0


def pod_type() -> Optional[str]:
    """TPU pod/slice type, e.g. "v5e-16" (_get_current_node_tpu_pod_type)."""
    t = os.environ.get(ACCELERATOR_TYPE_ENV)
    if t:
        return t
    gen = os.environ.get(_AXON_GEN_ENV)
    if gen:
        return f"{gen}-{max(num_tpu_chips(), 1)}"
    return None


def accelerator_type() -> Optional[str]:
    """Marker-resource name, e.g. "TPU-V5E" (get_current_node_accelerator_type)."""
    t = pod_type()
    if not t:
        return None
    return "TPU-" + t.split("-")[0].upper()


def worker_id() -> Optional[int]:
    v = os.environ.get(WORKER_ID_ENV)
    try:
        return int(v) if v is not None else None
    except ValueError:
        return None


def pod_name() -> Optional[str]:
    return os.environ.get(POD_NAME_ENV)


def _cores_per_chip(gen: str) -> int:
    # pod-type suffixes count TensorCores on v2-v4/v5p (2 per chip) but
    # chips on the single-core-per-chip efficiency gens (v5e/v6e)
    return 1 if gen in ("v5e", "v5litepod", "v6e") else 2


def num_workers_in_pod() -> Optional[int]:
    """Hosts in this pod slice = slice cores-or-chips / per-host equivalent
    (get_num_workers_in_current_tpu_pod analogue)."""
    t = pod_type()
    per_host = num_tpu_chips()
    if not t or per_host <= 0:
        return None
    try:
        gen, suffix = t.split("-")[0], int(t.split("-")[1])
    except (IndexError, ValueError):
        return None
    return max(1, suffix // (per_host * _cores_per_chip(gen)))


def validate_chip_request(n: float) -> None:
    """TPU requests must be 1/2/4/8 chips (ICI-connected groups) or a
    positive fraction <1 of one chip (validate_resource_request_quantity)."""
    if n <= 0:
        raise ValueError(f"TPU request must be positive, got {n}")
    if n < 1:
        return
    if n != int(n) or int(n) not in VALID_CHIP_REQUESTS:
        raise ValueError(
            f"TPU request of {n} is invalid: whole-chip requests must be one "
            f"of {VALID_CHIP_REQUESTS} (chips in an ICI-connected group)"
        )


class ChipAllocator:
    """Per-host chip assignment for spawned TPU workers.

    Least-loaded assignment: 1:1 pinning while workers <= chips, and stable
    sharing (never an unrestricted view) once fractional requests oversubscribe
    a chip.  Honors a parent process's TPU_VISIBLE_CHIPS restriction — ids are
    drawn from that set, not range(n).
    """

    def __init__(self, n_chips: int):
        vis = visible_chip_ids()
        ids = vis if vis is not None else [str(i) for i in range(max(n_chips, 0))]
        self._load: Dict[str, int] = {cid: 0 for cid in ids}

    def acquire(self) -> Optional[str]:
        if not self._load:
            return None
        cid = min(self._load, key=lambda c: (self._load[c], c))
        self._load[cid] += 1
        return cid

    def release(self, cid: Optional[str]) -> None:
        if cid is not None and self._load.get(cid, 0) > 0:
            self._load[cid] -= 1


def additional_resources() -> Dict[str, float]:
    """Topology-derived resources beyond the TPU chip count: the
    accelerator-type marker and, on worker 0 only, the pod-head resource
    (get_current_node_additional_resources analogue)."""
    out: Dict[str, float] = {}
    chips = num_tpu_chips()
    if chips <= 0:
        return out
    at = accelerator_type()
    if at:
        out[at] = float(chips)
    pt = pod_type()
    wid = worker_id()
    if pt and (wid == 0 or (wid is None and os.environ.get(_AXON_GEN_ENV))):
        out[f"TPU-{pt}-head"] = 1.0
    return out


def node_labels() -> Dict[str, str]:
    """Topology labels this node registers with the head, feeding
    NodeLabelSchedulingStrategy (the reference's ray.io/* node labels +
    the TPU fields its autoscaler puts in node metadata).  Keys:

      ca.io/accelerator-type   "TPU-V5E" marker (generation, upper-case)
      ca.io/tpu-generation     "v5e"
      ca.io/tpu-pod-type       "v5e-16" (slice type)
      ca.io/tpu-topology       TPU_CHIPS_PER_HOST_BOUNDS, e.g. "2,2,1"
      ca.io/tpu-slice-name     TPU_NAME (pod/slice identity for gang placement)
      ca.io/tpu-worker-id      "0".."N-1" within the slice
    """
    out: Dict[str, str] = {}
    if num_tpu_chips() <= 0:
        return out
    at = accelerator_type()
    if at:
        out["ca.io/accelerator-type"] = at
    pt = pod_type()
    if pt:
        out["ca.io/tpu-pod-type"] = pt
        out["ca.io/tpu-generation"] = pt.split("-")[0]
    bounds = os.environ.get(CHIPS_PER_HOST_BOUNDS_ENV)
    if bounds:
        out["ca.io/tpu-topology"] = bounds
    nm = pod_name()
    if nm:
        out["ca.io/tpu-slice-name"] = nm
    wid = worker_id()
    if wid is not None:
        out["ca.io/tpu-worker-id"] = str(wid)
    return out


def detect_node_labels(node_id: Optional[str] = None) -> Dict[str, str]:
    """The one label-derivation used by every node: auto-detected TPU
    topology labels + CA_NODE_LABELS env overrides (+ ca.io/node-id when the
    caller knows it).  Head-embedded node and agents must share this, or
    NodeLabelSchedulingStrategy selectors behave differently per node kind."""
    labels = dict(node_labels())
    labels.update(parse_labels_env(os.environ.get("CA_NODE_LABELS")))
    if node_id is not None:
        labels["ca.io/node-id"] = node_id
    return labels


def parse_labels_env(env_val: Optional[str]) -> Dict[str, str]:
    """Parse a CA_NODE_LABELS-style JSON object into a str->str label map;
    malformed or non-object JSON yields {} (a bad env var must not kill a
    node agent at startup)."""
    if not env_val:
        return {}
    import json

    try:
        obj = json.loads(env_val)
    except ValueError:
        return {}
    if not isinstance(obj, dict):
        return {}
    return {str(k): str(v) for k, v in obj.items()}


def visible_chips_env_for_worker(chip_id) -> Dict[str, str]:
    """Env a spawned TPU-pool worker should receive to pin it to one chip
    (set_current_process_visible_accelerator_ids analogue).  Empty when
    pinning is disabled or no chip was assigned."""
    if chip_id is None or os.environ.get(NOSET_VISIBLE_CHIPS_ENV):
        return {}
    return {VISIBLE_CHIPS_ENV: str(chip_id)}
