"""Unique identifiers for jobs, tasks, actors, objects, nodes and workers.

Design parity: the reference packs lineage metadata into its IDs
(src/ray/common/id.h). We keep the same *derivation* property — an ObjectID is
derived from the TaskID that produces it plus a return index — so that lineage
reconstruction can recover "which task created this object" from the ID alone.
IDs are fixed-width random bytes, hex-printable.
"""

from __future__ import annotations

import os
import threading

# Fast unique-byte generator for hot ID paths (task submission creates 2+ IDs
# per call; os.urandom is a ~15-20us getrandom syscall each).  Uniqueness =
# per-process random prefix (refreshed on fork, keyed by pid) + 6-byte counter.
_uniq_lock = threading.Lock()
_uniq_pid: int = -1
_uniq_prefix: bytes = b""
_uniq_count: int = 0


def _unique_bytes(n: int) -> bytes:
    """n unique bytes: (n-6)-byte per-process random prefix + 6-byte counter
    (2^48 ids/process).  Cross-process collision bound is the prefix's
    min(n-6, 16) random bytes — >= 2^48 for the 12-byte TaskID suffix."""
    global _uniq_pid, _uniq_prefix, _uniq_count
    with _uniq_lock:
        pid = os.getpid()
        if pid != _uniq_pid:
            _uniq_pid = pid
            _uniq_prefix = os.urandom(16)
            _uniq_count = 0
        _uniq_count += 1
        c = _uniq_count
    return _uniq_prefix[: n - 6] + c.to_bytes(6, "big")

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12
_TASK_ID_SIZE = 16
_OBJECT_ID_SIZE = 20
_NODE_ID_SIZE = 16
_WORKER_ID_SIZE = 16
_PG_ID_SIZE = 16

NIL = b"\x00"


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(NIL * cls.SIZE)

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == NIL * self.SIZE

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        # IDs key every hot-path dict (pending stores, ref counts, holders);
        # cache the hash instead of rehashing 16-20 bytes per lookup
        h = self._hash
        if h is None:
            h = self._hash = hash(self._bytes)
        return h

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE :])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_unique_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        # Not derived from the 12-byte ActorID: embedding it would leave only
        # 4 distinguishing bytes — colliding with realistic call counts
        # (birthday bound ~2^16 calls).  Actor attribution lives in the task
        # spec instead.
        return cls(_unique_bytes(cls.SIZE))


class ObjectID(BaseID):
    """Derived from (producing TaskID, return index): first 16 bytes are the
    TaskID, last 4 bytes the big-endian return index. `ray.put` objects use a
    put-index with the high bit set, mirroring the reference's put/return split
    (src/ray/common/id.h ObjectID::FromIndex)."""

    SIZE = _OBJECT_ID_SIZE
    _PUT_BIT = 0x80000000

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + (cls._PUT_BIT | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[-4:], "big") & self._PUT_BIT)

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[-4:], "big") & ~self._PUT_BIT


class NodeID(BaseID):
    SIZE = _NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _WORKER_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = _PG_ID_SIZE


class _Counter:
    """Thread-safe monotonically increasing counter (per-process)."""

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
