"""Node agent: the per-node daemon (raylet analogue, src/ray/raylet/
node_manager.h) for every node other than the head's own.

Responsibilities, mirroring the reference raylet:
- register the node (its resources) with the head over TCP and heartbeat;
- spawn/kill/monitor this node's worker processes on head request
  (worker_pool.h role) and report their deaths;
- grant worker leases NODE-LOCALLY out of head-delegated "lease blocks"
  (the LocalTaskManager/raylet-grant analogue, see LeaseGranter below);
- serve chunked reads of this node's shm objects for node-to-node transfer
  (object_manager.h push analogue);
- sweep departed clients' arena files and clean the node's shm namespace on
  shutdown.

Lease plane: the head remains the global placement policy (node choice,
spillover, PG bundle charging, fairness) but delegates bounded per-pool
lease capacity to each agent as lease blocks — specific registered idle
workers whose unit resource shape the head pre-charges against the node.
Submitters dial this agent directly (`lease_grant`/`lease_release`) for the
hot unit-shape lease class, so steady-state task floods never touch the
head's loop; exhausted blocks and every other lease class fall back to the
head, which also revokes delegated capacity on demand and reclaims it
wholesale when an agent dies.  Task pushes still go driver->worker directly;
the agent is only on the lease path, never the task path.
"""

from __future__ import annotations

import asyncio
import os
import random
import signal
import subprocess
import sys
import time
from typing import Any, Dict, Optional

from . import netchaos
from .config import CAConfig, set_config
from .errors import FencedError
from .head import read_shm_chunk
from .ownership import DeltaReporter, quantize_load
from .protocol import AddrRing, Server, addr_list, spawn_bg


def node_load_sample() -> Dict[str, float]:
    """Point-in-time node utilization, disseminated with heartbeats (the
    centralized stand-in for ray_syncer.h:83's NodeResourceUsage broadcast:
    one scheduler needs the data, so it flows head-ward, not peer-to-peer)."""
    out: Dict[str, float] = {}
    try:
        out["load_1m"] = os.getloadavg()[0]
    except OSError:
        pass
    try:
        from .memory_monitor import MemoryMonitor

        s = MemoryMonitor().sample()
        if s is not None:
            used, total = s
            out["mem_used_frac"] = round(used / total, 4) if total else 0.0
    except Exception:
        pass
    return out


class LeaseGranter:
    """Node-local lease granting over head-delegated lease blocks (the
    LocalTaskManager analogue of src/ray/raylet/local_task_manager.h).

    The head delegates specific idle workers (wid + dialable address) per
    pool; their unit resource shape was charged against the node centrally
    at delegation time, so granting here requires no further accounting —
    a grant is a dictionary move.  Lease liveness is connection liveness:
    each lease remembers the granting client's connection state, and the
    agent releases every lease of a departed connection (mirroring the
    head's client-disconnect lease sweep).  Worker death (reaped by the
    agent) frees the slot and shrinks the block.
    """

    def __init__(self, node_id: str):
        self.node_id = node_id
        # pool -> wid -> {"addr": str, "lease": Optional[str]}
        self.workers: Dict[str, Dict[str, dict]] = {}
        # lease_id -> (pool, wid, granting conn-state dict)
        self.leases: Dict[str, tuple] = {}
        # per-pool lifetime counters (attribution must stay per pool: the
        # head sums them across pools for ca status / lease_plane())
        self.counters: Dict[str, Dict[str, int]] = {}
        self._seq = 0

    def _pool_counters(self, pool: str) -> Dict[str, int]:
        return self.counters.setdefault(
            pool, {"granted": 0, "denied": 0, "released": 0, "revoked": 0}
        )

    def add_workers(self, pool: str, workers) -> int:
        """Absorb a lease_block delegation; duplicate wids are idempotent
        (re-delegation after head-restart reconciliation)."""
        slot = self.workers.setdefault(pool, {})
        added = 0
        for w in workers or ():
            if w["wid"] not in slot:
                slot[w["wid"]] = {"addr": w["addr"], "lease": None}
                added += 1
        return added

    def grant(self, pool: str, conn_state) -> Optional[dict]:
        """Grant one unit-shape lease from the pool's block, or None when
        the block is exhausted (the submitter falls back to the head)."""
        for wid, ent in self.workers.get(pool, {}).items():
            if ent["lease"] is None:
                self._seq += 1
                lease_id = f"L{self.node_id}:{self._seq}:{os.urandom(3).hex()}"
                ent["lease"] = lease_id
                self.leases[lease_id] = (pool, wid, conn_state)
                self._pool_counters(pool)["granted"] += 1
                return {"lease_id": lease_id, "worker_id": wid, "addr": ent["addr"]}
        self._pool_counters(pool)["denied"] += 1
        return None

    def release(self, lease_id: str) -> None:
        rec = self.leases.pop(lease_id, None)
        if rec is None:
            return  # idempotent: worker-exit or disconnect already freed it
        pool, wid, _ = rec
        ent = self.workers.get(pool, {}).get(wid)
        if ent is not None and ent["lease"] == lease_id:
            ent["lease"] = None
        self._pool_counters(pool)["released"] += 1

    def release_for_conn(self, conn_state) -> int:
        """A granting client's connection closed: its leases are dead (the
        agent-side analogue of the head's disconnect lease sweep)."""
        gone = [lid for lid, (_, _, st) in self.leases.items() if st is conn_state]
        for lid in gone:
            self.release(lid)
        return len(gone)

    def on_worker_exit(self, wid: str) -> None:
        for pool, slot in self.workers.items():
            ent = slot.pop(wid, None)
            if ent is not None:
                if ent["lease"] is not None:
                    self.leases.pop(ent["lease"], None)
                return

    def revoke(self, pool: str, n: int) -> list:
        """Give back up to n UNLEASED workers (head revocation / fairness
        reclaim); outstanding grants keep their workers."""
        out = []
        slot = self.workers.get(pool, {})
        for wid in list(slot):
            if len(out) >= n:
                break
            if slot[wid]["lease"] is None:
                del slot[wid]
                out.append(wid)
        self._pool_counters(pool)["revoked"] += len(out)
        return out

    def stats(self) -> Dict[str, dict]:
        """Per-pool block occupancy + lifetime counters, shipped to the head
        with every heartbeat (the existing dissemination path)."""
        out = {}
        for pool, slot in self.workers.items():
            used = sum(1 for e in slot.values() if e["lease"] is not None)
            out[pool] = {"size": len(slot), "used": used, **self._pool_counters(pool)}
        return out

    def block_snapshot(self) -> Dict[str, dict]:
        """What a (re)registration reports so a restarted head re-adopts the
        delegated blocks instead of double-granting the same workers."""
        return {
            pool: {
                "wids": list(slot),
                "used": sum(1 for e in slot.values() if e["lease"] is not None),
            }
            for pool, slot in self.workers.items()
            if slot
        }


class NodeAgent:
    def __init__(self):
        self.session_dir = os.environ["CA_SESSION_DIR"]
        self.session_name = os.path.basename(self.session_dir)
        # CA_HEAD_ADDR may be a comma-separated list (active head first,
        # warm standbys after): the ring rotates through candidates on
        # failover, and register replies merge in standbys learned later
        self._head_ring = AddrRing(addr_list(os.environ["CA_HEAD_ADDR"]))
        self.head_addr = self._head_ring.current or os.environ["CA_HEAD_ADDR"]
        self.node_id = os.environ["CA_NODE_ID"]
        import json

        self.resources = json.loads(os.environ.get("CA_NODE_RESOURCES", '{"CPU": 4}'))
        # labels travel with registration: detected HERE (the agent's env,
        # not the head's); the head adds ca.io/node-id when recording
        from .accelerators import detect_node_labels

        self.labels = detect_node_labels()
        self.config = CAConfig.from_json(os.environ["CA_CONFIG_JSON"])
        set_config(self.config)
        self.serve_addr_spec = os.environ.get("CA_AGENT_SERVE", "tcp:127.0.0.1:0")
        self.node_dir = os.path.join(self.session_dir, "nodes", self.node_id)
        os.makedirs(self.node_dir, exist_ok=True)
        if self.config.log_capture:
            # the agent captures its own output the same way its workers do:
            # agent.jsonl rides the same tail-and-ship loop, so agent prints
            # reach subscribed drivers prefixed "(agent ... node=...)"
            from ..util.logplane import install_capture

            install_capture(
                self.session_dir, self.node_id, "agent",
                max_bytes=self.config.log_rotate_bytes,
            )
        self.shm_ns_dir = os.path.join("/dev/shm", self.session_name, self.node_id)
        os.makedirs(self.shm_ns_dir, exist_ok=True)
        self.server = Server(
            [self.serve_addr_spec], self._handle, on_disconnect=self._on_client_gone
        )
        # node-local lease granting over head-delegated blocks (raylet
        # LocalTaskManager analogue)
        self.granter = LeaseGranter(self.node_id)
        # chip pinning for this node's TPU workers (same policy as the head's
        # local node; the agent owns spawns here, so it owns the allocator)
        from .accelerators import ChipAllocator

        n_chips = int(self.resources.get("TPU", 0))
        self.chip_alloc = ChipAllocator(n_chips) if n_chips > 1 else None
        self._worker_chips: Dict[str, str] = {}
        self.mem_monitor = None
        if self.config.memory_monitor_refresh_ms > 0 and self.config.memory_usage_threshold > 0:
            from .memory_monitor import MemoryMonitor

            self.mem_monitor = MemoryMonitor(self.config.memory_usage_threshold)
        self.head = None
        self.procs: Dict[str, subprocess.Popen] = {}  # wid -> proc
        self._pull_maps: Dict[str, Any] = {}
        self._shutdown = asyncio.Event()
        self._draining = False  # SIGTERM self-drain already requested
        # fencing token minted by the head at registration; stamped onto
        # every authority-bearing notify (node_sync, worker_exit, block
        # returns) so a partitioned-then-healed agent is refused instead of
        # believed.  None = not yet registered / purged for a fresh rejoin.
        self.incarnation: Optional[int] = None
        self._fencing = False  # single-flight guard for _fence_reset
        # HA plane: highest head epoch this agent has observed (register
        # replies and hep-stamped head RPCs).  A call stamped with a LOWER
        # epoch comes from a superseded head (a zombie that healed from a
        # partition still believing it owns the cluster): refuse it with
        # FencedError — the refusal is how the old head learns to demote.
        self.head_epoch = 0
        self.ha_zombie_rpcs = 0  # fenced old-head calls (chaos test hook)
        # network-chaos plane: partition/straggler injection from the spec
        # this process was started with (runtime `ca chaos set` broadcasts
        # arrive as net_chaos pushes)
        netchaos.maybe_install_from_config(self.config, self.node_id)
        # delta-synced node state (ray_syncer role, head-ward): components
        # re-send only when their payload changes; an idle node's tick
        # degenerates to a bare node_sync keepalive.  reset() on every
        # (re)registration forces a full resync to the (new) head.
        self.reporter = DeltaReporter()
        self._mp_tick = 0  # re-send the pressure component while pressured
        # metrics plane: this node's aggregated metrics table (the per-node
        # MetricsAgent role).  Workers ship delta records here instead of to
        # the head; the table is served over HTTP in Prometheus exposition
        # format (head-free scrape) and the deltas piggyback onto node_sync
        # ticks so the head's cluster-wide table stays fed for dashboards.
        self.node_metrics: Dict[str, dict] = {}
        self._metrics_pending: list = []
        self.metrics_stats = {
            "reports_total": 0, "scrapes_total": 0, "head_ship_dropped": 0,
        }
        self._http_server = None
        self.metrics_addr = None
        # flight recorder: the agent journals its own decisions (fence
        # resets, drain handling) and forwards workers' journal slices
        # head-ward on the same node_sync piggyback as metric deltas
        self._flightrec_pending: list = []
        if getattr(self.config, "flightrec_plane", True):
            from ..util import flightrec

            flightrec.init(
                cap=getattr(self.config, "flightrec_ring_len", 4096),
                node_id=self.node_id, proc="agent",
            )

    # --------------------------------------------------------------- workers
    def _spawn_worker(self, wid: str, purpose: str, pool: str) -> None:
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = self.session_dir
        # workers dial the head over TCP; they inherit the whole head ring
        # (live active first) so a worker spawned pre-failover can re-anchor
        # to a promoted standby it never registered with
        ring = list(self._head_ring.addrs)
        if self.head_addr in ring:
            ring.remove(self.head_addr)
        env["CA_HEAD_SOCK"] = ",".join([self.head_addr] + ring)
        env["CA_WORKER_ID"] = wid
        env["CA_WORKER_SOCK"] = "tcp:127.0.0.1:0"  # bind ephemeral, advertise
        env["CA_NODE_ID"] = self.node_id
        env["CA_AGENT_ADDR"] = self.serve_addr  # local pulls dedup through us
        env["CA_CONFIG_JSON"] = self.config.to_json()
        if pool != "tpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        elif self.chip_alloc is not None:
            from .accelerators import visible_chips_env_for_worker

            chip = self.chip_alloc.acquire()
            if chip is not None:
                self._worker_chips[wid] = chip
                env.update(visible_chips_env_for_worker(chip))
        log_path = os.path.join(self.node_dir, f"{wid}.log")
        logf = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.workerproc"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        self.procs[wid] = proc

    def _kill_worker(self, wid: str):
        proc = self.procs.get(wid)
        if proc is not None and proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass

    # --------------------------------------------------------------- handler
    async def _on_client_gone(self, state):
        # a submitter's connection died: its locally-granted leases are dead
        # (lease liveness IS connection liveness on the local plane)
        self.granter.release_for_conn(state)

    async def _handle(self, state, msg, reply, reply_err):
        m = msg["m"]
        hep = msg.get("hep")
        if hep is not None:
            if hep > self.head_epoch:
                self.head_epoch = hep
            elif hep < self.head_epoch:
                # a superseded head's RPC (zombie authority): refuse and tell
                # it WHY — the "head epoch" marker in the message is the old
                # head's demote trigger.  Never execute the body: spawns and
                # kills from a fenced head are duplicate side effects.
                self.ha_zombie_rpcs += 1
                from ..util import flightrec

                if flightrec.REC is not None:
                    flightrec.REC.record(
                        "ha", "ha_fence_old_head",
                        method=m, offered=hep, known=self.head_epoch,
                    )
                reply_err(FencedError(
                    f"call stamped by superseded head epoch {hep} "
                    f"(current head epoch: {self.head_epoch})"
                ))
                return
        if m == "lease_grant":
            # node-local grant (hot path): a dict move, no head round-trip.
            # An exhausted block replies granted=False — the submitter falls
            # back to the head, which may revoke/re-balance capacity.
            g = self.granter.grant(msg.get("pool", "cpu"), state)
            if g is None:
                reply(granted=False)
            else:
                # grants carry the node incarnation: a post-heal audit can
                # prove no outstanding grant was minted pre-verdict
                reply(granted=True, ninc=self.incarnation, **g)
        elif m == "lease_release":
            for lid in msg.get("lease_ids") or ():
                self.granter.release(lid)
            reply()
        elif m == "lease_block":
            # head delegation push: absorb the block's workers — unless the
            # delegation names a different incarnation (this agent is
            # mid-fence: granting from a stale block would mint zombies)
            if msg.get("ninc") is not None and msg["ninc"] != self.incarnation:
                reply(rejected=True)
            else:
                self.granter.add_workers(msg.get("pool", "cpu"), msg.get("workers"))
                reply()
        elif m == "lease_block_revoke":
            # head wants capacity back (pending central work / fairness):
            # return unleased workers; outstanding grants keep theirs
            pool = msg.get("pool", "cpu")
            wids = self.granter.revoke(pool, int(msg.get("n", 1 << 30)))
            if wids:
                try:
                    self.head.notify(
                        "lease_block_return",
                        **self._auth(
                            {"node_id": self.node_id, "pool": pool, "wids": wids}
                        ),
                    )
                except Exception:
                    pass  # head gone: re-register reconciles the block
            reply(wids=wids)
        elif m == "spawn_worker":
            self._spawn_worker(msg["wid"], msg.get("purpose", "pool"), msg.get("pool", "cpu"))
            reply()
        elif m == "kill_worker":
            self._kill_worker(msg["wid"])
            reply()
        elif m == "log_read":
            # query plane: the head proxies cross-node log reads through the
            # owning agent, so `ca logs`/get_log need no shared filesystem
            from ..util.logplane import tail_file

            name = msg["name"]
            if "/" in name or ".." in name or name.startswith("."):
                reply_err(ValueError(f"bad log name {name!r}"))
                return
            suffix = ".jsonl" if msg.get("structured") else ".log"
            path = os.path.join(self.node_dir, name + suffix)
            try:
                data, off = tail_file(
                    path, tail=int(msg.get("tail", 200)), off=msg.get("off")
                )
            except (FileNotFoundError, OSError):
                reply_err(FileNotFoundError(
                    f"no log for {name!r} on node {self.node_id}"
                ))
            else:
                reply(data=data, off=off, node_id=self.node_id)
        elif m == "pull_chunk":
            delay = getattr(self.config, "testing_transfer_delay_s", 0.0)
            if delay:
                # test/bench hook: simulated link latency (see head twin)
                await asyncio.sleep(delay)
            reply(data=read_shm_chunk(
                self.session_name, self._pull_maps, msg["shm_name"], msg["off"], msg["len"]
            ))
        elif m == "sweep_arenas":
            import glob

            for path in glob.glob(os.path.join(self.shm_ns_dir, f"arena_{msg['cid']}_*")):
                name = os.path.relpath(path, "/dev/shm")
                mm = self._pull_maps.pop(name, None)
                if mm is not None:
                    try:
                        mm.close()
                    except (BufferError, ValueError):
                        pass
                try:
                    os.unlink(path)
                except OSError:
                    pass
            reply()
        elif m == "unlink_shm":
            name = msg["shm_name"]
            if name.startswith(f"{self.session_name}/{self.node_id}/") and ".." not in name:
                from .head import drop_pull_map

                drop_pull_map(self._pull_maps, name)
                try:
                    os.unlink(os.path.join("/dev/shm", name))
                except OSError:
                    pass
        elif m == "unlink_spill":
            path = msg["path"]
            if f"/{self.session_name}/" in path and "/spill/" in path and ".." not in path:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        elif m == "metrics_report":
            # metrics plane ingest: a local worker's delta batch lands in the
            # node table (scrape truth, head-free) and queues for the next
            # node_sync tick (head dashboard truth).  The pending queue is
            # bounded like the worker-side re-stage buffer: a long head
            # outage drops the OLDEST deltas, never the node table.
            from ..util.metrics import RESTAGE_CAP, merge_metric_records

            records = msg.get("metrics") or []
            merge_metric_records(self.node_metrics, records)
            self.metrics_stats["reports_total"] += len(records)
            self._metrics_pending.extend(records)
            over = len(self._metrics_pending) - RESTAGE_CAP
            if over > 0:
                del self._metrics_pending[:over]
                self.metrics_stats["head_ship_dropped"] += over
                from .ownership import warn_ratelimited

                warn_ratelimited(
                    "agent-metrics-pending-cap",
                    f"node {self.node_id}: metrics head-ship queue full, "
                    f"dropped {over} oldest delta records",
                )
            # flight-recorder piggyback: worker journal slices queue for the
            # next node_sync tick, bounded with the same drop-oldest policy
            frev = msg.get("flightrec") or []
            if frev:
                from ..util.flightrec import FLIGHTREC_STATS

                self._flightrec_pending.extend(frev)
                over = len(self._flightrec_pending) - RESTAGE_CAP
                if over > 0:
                    del self._flightrec_pending[:over]
                    FLIGHTREC_STATS["dropped"] += over
        elif m == "profile":
            # sampling profiler relay target: profile THIS agent process
            # (workers serve their own `profile`; the head resolves routing)
            from ..util import profiler

            res = await asyncio.get_running_loop().run_in_executor(
                None, profiler.sample_stacks,
                float(msg.get("duration", 2.0)), float(msg.get("hz", 100.0)),
            )
            reply(
                folded=profiler.render_folded(res["folded"]),
                speedscope=profiler.speedscope_json(
                    res["folded"], f"agent {self.node_id}", res["hz"]
                ),
                samples=res["samples"],
                duration_s=res["duration_s"],
            )
        elif m == "node_shutdown":
            self._shutdown.set()
        elif m == "net_chaos":
            # runtime chaos broadcast from the head (`ca chaos set`)
            try:
                netchaos.install(
                    msg.get("spec") or "", self.node_id,
                    epoch=msg.get("epoch"),
                )
            except (ValueError, TypeError):
                pass  # malformed spec was already rejected head-side
            reply()
        elif m == "fenced":
            # the head refused one of our stamped RPCs: this incarnation
            # (echoed in the push) was declared dead — purge and rejoin
            # fresh (zombie-free heal)
            if msg.get("ninc") is None or msg.get("ninc") == self.incarnation:
                spawn_bg(self._fence_reset())
            reply()
        elif m == "ha_ring":
            # runtime standby-ring dissemination (HA plane): an agent that
            # registered before any standby subscribed learns failover
            # targets here, not just via its register reply
            self._head_ring.merge(msg.get("standbys") or [])
            ep = msg.get("head_epoch")
            if ep is not None and ep > self.head_epoch:
                self.head_epoch = ep
            reply()
        # operator liveness probe: ca-lint: ignore[rpc-dead-handler]
        elif m == "ping":
            reply(node_id=self.node_id, n_workers=len(self.procs),
                  head_epoch=self.head_epoch)
        else:
            reply_err(ValueError(f"unknown agent method {m}"))

    # ------------------------------------------------------- metrics scrape
    def _scrape_table(self) -> Dict[str, dict]:
        """The node table plus the agent's own liveness counters — what a
        Prometheus scrape of this node returns."""
        table = dict(self.node_metrics)
        tags = "[]"
        table["ca_node_agent_metrics_reports_total"] = {
            "type": "counter",
            "desc": "worker metric delta records ingested by this node agent",
            "data": {tags: float(self.metrics_stats["reports_total"])},
        }
        table["ca_node_agent_scrapes_total"] = {
            "type": "counter",
            "desc": "HTTP /metrics scrapes served by this node agent",
            "data": {tags: float(self.metrics_stats["scrapes_total"])},
        }
        table["ca_node_agent_workers"] = {
            "type": "gauge",
            "desc": "worker processes currently supervised by this agent",
            "data": {tags: float(len(self.procs))},
        }
        table["ca_node_agent_head_ship_dropped_total"] = {
            "type": "counter",
            "desc": "metric delta records dropped at this agent's bounded "
            "head-ship queue (head unreachable too long)",
            "data": {tags: float(self.metrics_stats["head_ship_dropped"])},
        }
        return table

    async def _http_client(self, reader, writer):
        """Minimal HTTP endpoint: GET /metrics (Prometheus exposition text
        of this node's table — served with NO head involvement, so scrapes
        survive a dead head) and GET /healthz."""
        try:
            req = await asyncio.wait_for(reader.readline(), 10)
            parts = req.decode("latin1").split()
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = parts[1].split("?", 1)[0] if len(parts) >= 2 else ""
            if len(parts) < 2 or parts[0] != "GET":
                status, ctype, body = 405, "text/plain", b"GET only"
            elif path == "/metrics":
                from ..util.metrics import render_prometheus

                self.metrics_stats["scrapes_total"] += 1
                body = render_prometheus(self._scrape_table()).encode()
                status, ctype = 200, "text/plain; version=0.0.4"
            elif path == "/healthz":
                status, ctype, body = 200, "text/plain", b"ok\n"
            else:
                status, ctype, body = 404, "text/plain", b"not found"
            reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed"}[status]
            writer.write(
                f"HTTP/1.1 {status} {reason}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
            )
            writer.write(body)
            from ..util.aio import drain  # lazy: util/__init__ reaches into core

            await drain(writer, timeout=10)
        except asyncio.CancelledError:
            raise  # agent shutdown: the finally still closes the socket
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _routable_host(self):
        """This host's address on the interface that routes to the head (a
        connected UDP socket never sends a packet; getsockname reveals the
        chosen source address)."""
        import socket

        head = self.head_addr
        if not head.startswith("tcp:"):
            return None
        head_host = head[4:].rpartition(":")[0]
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((head_host, 9))
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            return None

    async def _start_metrics_http(self):
        """Bind the scrape endpoint (host of the agent's RPC listener,
        CA_AGENT_METRICS_PORT or ephemeral) and advertise it: in the node
        dir for same-host tools and in the register payload for `ca
        metrics --node` / the dashboard."""
        host = "127.0.0.1"
        spec = self.serve_addr_spec
        if spec.startswith("tcp:"):
            host = spec.split(":")[1] or "127.0.0.1"
        port = int(os.environ.get("CA_AGENT_METRICS_PORT", "0"))
        try:
            self._http_server = await asyncio.start_server(
                self._http_client, host, port
            )
        except OSError:
            return  # port taken: the node runs without a scrape endpoint
        h, p = self._http_server.sockets[0].getsockname()[:2]
        if h in ("0.0.0.0", "::", ""):
            # a wildcard bind must not be ADVERTISED as-is (Prometheus and
            # `ca metrics --node` would dial 0.0.0.0): use the interface
            # that routes to the head — the address peers reach us on
            h = self._routable_host() or "127.0.0.1"
        self.metrics_addr = f"http://{h}:{p}"
        path = os.path.join(self.node_dir, "metrics.addr")
        with open(path + ".tmp", "w") as f:
            f.write(self.metrics_addr)
        os.replace(path + ".tmp", path)

    # ------------------------------------------------------------ lifecycle
    def _auth(self, fields: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp an authority-bearing head notify with this node's
        incarnation (fencing: a stale stamp is refused, and the refusal is
        how a healed zombie learns its death verdict).  The head epoch rides
        beside it: a demoted head that still answers this node's RPCs sees
        its successor's epoch and learns the same verdict in reverse."""
        if self.incarnation is not None:
            fields["ninc"] = self.incarnation
        if self.head_epoch:
            fields["hep"] = self.head_epoch
        return fields

    async def _heartbeat_loop(self):
        period = self.config.health_check_period_s / 2
        while not self._shutdown.is_set():
            await asyncio.sleep(min(period, 1.0))
            try:
                if getattr(self.config, "delta_sync", True):
                    self._send_node_sync()
                else:
                    hb = {"node_id": self.node_id, "load": node_load_sample()}
                    if self.mem_monitor is not None:
                        hb["mem_pressured"] = self.mem_monitor.is_pressured()
                    # delegated/used block occupancy rides the heartbeat (the
                    # same dissemination path as load): the head's `ca
                    # status`, /api/nodes, and revocation sizing read it
                    hb["lease_stats"] = self.granter.stats()
                    pending = (
                        self._take_pending_metrics()
                        if self._metrics_pending else []
                    )
                    if pending:
                        hb["metrics"] = pending
                    frp = self._take_pending_flightrec()
                    if frp:
                        hb["flightrec"] = frp
                    try:
                        self.head.notify("node_heartbeat", **self._auth(hb))
                    except Exception:
                        if pending:
                            self._restage_pending_metrics(pending)
                        if frp:
                            self._restage_pending_flightrec(frp)
                        raise
            except Exception:
                pass
            # reap exited worker processes and report them (the head cannot
            # poll processes it didn't spawn)
            for wid, proc in list(self.procs.items()):
                if proc.poll() is not None:
                    del self.procs[wid]
                    # free the lease slot first: a delegated worker's death
                    # shrinks the block and kills its outstanding grant
                    self.granter.on_worker_exit(wid)
                    if self.chip_alloc is not None:
                        self.chip_alloc.release(self._worker_chips.pop(wid, None))
                    try:
                        self.head.notify(
                            "worker_exit", **self._auth({"wid": wid})
                        )
                    except Exception:
                        pass

    def _take_pending_metrics(self) -> list:
        pending, self._metrics_pending = self._metrics_pending, []
        return pending

    def _take_pending_flightrec(self) -> list:
        """Queued worker journal slices plus this agent's own unshipped
        events, in arrival order (the agent's recorder drains here — agents
        run no metrics flusher of their own)."""
        from ..util import flightrec

        pending, self._flightrec_pending = self._flightrec_pending, []
        if flightrec.REC is not None:
            pending.extend(flightrec.REC.drain())
        return pending

    def _restage_pending_flightrec(self, evs: list) -> None:
        from ..util.flightrec import FLIGHTREC_STATS
        from ..util.metrics import RESTAGE_CAP

        self._flightrec_pending[:0] = evs
        over = len(self._flightrec_pending) - RESTAGE_CAP
        if over > 0:
            del self._flightrec_pending[:over]
            FLIGHTREC_STATS["dropped"] += over

    def _restage_pending_metrics(self, records: list) -> None:
        """A head send failed after the queue was drained: put the records
        back at the FRONT (counter order matters at the aggregator), then
        enforce the cap with the same drop-OLDEST-and-count policy as the
        ingest path — the restaged batch is the oldest data in the queue."""
        from ..util.metrics import RESTAGE_CAP

        self._metrics_pending[:0] = records
        over = len(self._metrics_pending) - RESTAGE_CAP
        if over > 0:
            del self._metrics_pending[:over]
            self.metrics_stats["head_ship_dropped"] += over
            from .ownership import warn_ratelimited

            warn_ratelimited(
                "agent-metrics-pending-cap",
                f"node {self.node_id}: metrics head-ship queue full on "
                f"restage, dropped {over} oldest delta records",
            )

    def _send_node_sync(self):
        """Versioned delta heartbeat (node_sync): only components whose
        payload changed since the last send travel; an unchanged tick is a
        bare {node_id} keepalive (liveness only).  Load telemetry is
        quantized first — raw loadavg jitter would re-send the component
        every tick and make delta sync a full heartbeat with extra steps.
        The mem-pressure component re-sends every tick WHILE pressured: the
        head clears its flag after acting on it (kill one worker per refresh
        period), so a level-triggered single send would stop the policy
        after the first kill.  Queued worker metric deltas piggyback on the
        same tick (the metrics plane's head-ward dashboard feed) — they ride
        whatever frame the tick produces, keepalive included."""
        comps: Dict[str, Any] = {
            "load": quantize_load(node_load_sample()),
            "lease_stats": self.granter.stats(),
        }
        if self.mem_monitor is not None:
            if self.mem_monitor.is_pressured():
                self._mp_tick += 1
                comps["mem_pressured"] = [True, self._mp_tick]
            else:
                comps["mem_pressured"] = False
        d = self.reporter.delta(comps)
        extra: Dict[str, Any] = self._auth({})
        pending = self._take_pending_metrics() if self._metrics_pending else []
        if pending:
            extra["metrics"] = pending
        frp = self._take_pending_flightrec()
        if frp:
            extra["flightrec"] = frp
        try:
            if d is None:
                self.head.notify("node_sync", node_id=self.node_id, **extra)
            else:
                self.head.notify("node_sync", node_id=self.node_id, **d, **extra)
        except Exception:
            if pending:
                self._restage_pending_metrics(pending)
            if frp:
                self._restage_pending_flightrec(frp)
            raise

    async def _log_ship_loop(self):
        """Tail this node's structured capture files and batch new records
        to the head (log-monitor analogue).  The files are the buffer: a
        closed head connection just leaves records on disk for the next
        tick; only a send that fails after the tailer advanced is a loss
        (counted in ca_log_dropped_total)."""
        from ..util.logplane import LOG_STATS, LogTailer

        tailer = LogTailer(self.node_dir, max_records=self.config.log_ship_batch)
        period = max(self.config.log_ship_interval_s, 0.05)
        while not self._shutdown.is_set():
            await asyncio.sleep(period)
            if self.head is None or self.head.closed:
                continue
            try:
                records = tailer.poll()
            except Exception:
                continue
            if not records:
                continue
            try:
                # records carry their own node stamp; a top-level node_id
                # was wire bytes nothing read (ca lint rpc-unread-field)
                self.head.notify("log_batch", records=records)
            except Exception:
                LOG_STATS["dropped_total"] += len(records)

    async def _on_head_push(self, msg):
        # the head reaches us both through its own connection (requests)
        # and as pushes on ours; route pushes through the same handler
        if "m" in msg:
            await self._handle({}, msg, lambda **kw: None, lambda e: None)

    async def _amain(self):
        await self.server.start()
        self.serve_addr = self.server.bound_addrs[0]
        if getattr(self.config, "metrics_plane", True):
            # scrape endpoint first: metrics_addr travels in the register
            await self._start_metrics_http()
        from ..util.aio import dial  # lazy: util/__init__ reaches into core

        netchaos.register_addr(self.head_addr, "n0")
        self.head = await dial(self.head_addr, purpose="head", peer_node="n0")
        self.head.set_push_handler(self._on_head_push)
        reply = await self.head.call(
            "register",
            role="agent",
            client_id=self.node_id,
            addr=self.serve_addr,
            resources=self.resources,
            labels=self.labels,
            pid=os.getpid(),
            lease_blocks=self.granter.block_snapshot(),
            metrics_addr=self.metrics_addr,
        )
        self._adopt_register_reply(reply)
        # readiness marker for the cluster fixture
        ready = os.path.join(self.node_dir, "agent.ready")
        with open(ready + ".tmp", "w") as f:
            f.write(f"{os.getpid()}\n{self.serve_addr}\n")
        os.replace(ready + ".tmp", ready)  # atomic: never visible half-written
        # preemption warning: spot/preemptible VMs deliver SIGTERM tens of
        # seconds before the kill — convert it into a head-driven drain
        # (zero-loss evacuation) instead of dying by heartbeat timeout
        try:
            loop = asyncio.get_running_loop()
            loop.add_signal_handler(
                signal.SIGTERM, lambda: spawn_bg(self._self_drain())
            )
        except (NotImplementedError, RuntimeError):
            pass  # non-unix loop: preemption warnings degrade to hard kills
        hb = spawn_bg(self._heartbeat_loop())
        head_watch = spawn_bg(self._watch_head())
        log_ship = spawn_bg(self._log_ship_loop())
        await self._shutdown.wait()
        hb.cancel()
        head_watch.cancel()
        log_ship.cancel()
        self._teardown()

    def _adopt_register_reply(self, reply: dict) -> None:
        """Take the head-minted incarnation (the authority token every
        stamped RPC carries), the head epoch and standby list (HA plane),
        and any active runtime chaos schedule."""
        if reply.get("incarnation") is not None:
            self.incarnation = reply["incarnation"]
        ep = reply.get("head_epoch")
        if ep is not None:
            self.head_epoch = max(self.head_epoch, int(ep))
        if reply.get("standbys"):
            self._head_ring.merge(reply["standbys"])
        if reply.get("net_chaos"):
            try:
                netchaos.install(
                    reply["net_chaos"], self.node_id,
                    epoch=reply.get("net_chaos_epoch"),
                )
            except (ValueError, TypeError):
                pass

    async def _fence_reset(self):
        """Zombie-free heal: this incarnation was declared dead while we
        were partitioned.  Everything minted under it must die BEFORE the
        node rejoins — workers (their tasks would complete duplicate side
        effects), delegated lease blocks and local grants (granting from
        them mints more zombies), the shm namespace (the head already
        declared those object copies lost), and the delta-sync state.  Then
        drop the incarnation token and force a re-register, which the head
        accepts as a FRESH node at a bumped incarnation."""
        if self._fencing:
            return
        self._fencing = True
        try:
            from ..util import flightrec
            from .ownership import warn_ratelimited

            if flightrec.REC is not None:
                flightrec.REC.record(
                    "fence", "fence_reset",
                    incarnation=self.incarnation, n_workers=len(self.procs),
                )
            warn_ratelimited(
                "agent-fenced",
                f"node {self.node_id} incarnation {self.incarnation} was "
                f"declared dead (partition?): purging workers/leases/shm "
                f"and rejoining fresh",
            )
            for wid in list(self.procs):
                self._kill_worker(wid)
            deadline = asyncio.get_running_loop().time() + 10.0
            while self.procs and asyncio.get_running_loop().time() < deadline:
                for wid, proc in list(self.procs.items()):
                    if proc.poll() is not None:
                        del self.procs[wid]
                        if self.chip_alloc is not None:
                            self.chip_alloc.release(
                                self._worker_chips.pop(wid, None)
                            )
                if self.procs:
                    await asyncio.sleep(0.05)
            # every local grant and delegated block dies with the verdict
            self.granter = LeaseGranter(self.node_id)
            self._worker_chips.clear()
            # the node's object copies were declared lost: sweep the
            # namespace so nothing serves stale reads out of it
            import shutil

            for name, mm in list(self._pull_maps.items()):
                try:
                    mm.close()
                except (BufferError, ValueError, OSError):
                    pass
                self._pull_maps.pop(name, None)
            shutil.rmtree(self.shm_ns_dir, ignore_errors=True)
            os.makedirs(self.shm_ns_dir, exist_ok=True)
            self.reporter.reset()
            self.incarnation = None  # rejoin as a fresh incarnation
            if self.head is not None and not self.head.closed:
                # drop the stale-stamped connection; _watch_head re-registers
                await self.head.close()
        finally:
            self._fencing = False

    async def _self_drain(self):
        """SIGTERM landed (preemption warning / graceful stop request): ask
        the head to drain this node instead of dying by heartbeat timeout.
        The agent keeps serving (object pulls, heartbeats, lease releases)
        through the evacuation window; the head's `node_shutdown` notify ends
        it.  A second SIGTERM — or an unreachable head — shuts down now."""
        if self._draining:
            self._shutdown.set()  # impatient supervisor: obey immediately
            return
        self._draining = True
        try:
            await self.head.call(
                "drain_node", node_id=self.node_id, reason="preemption",
                timeout=5,
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            # no head to evacuate through: the warning buys nothing — exit
            # so workers die with the process group, not mid-RPC later
            self._shutdown.set()

    async def _watch_head(self):
        """Watch the head connection, redialing through restarts (a restarted
        head re-adopts this node from its snapshot).  Tear down only when the
        head stays unreachable past the grace window — the reference raylet's
        GCS-unreachable exit."""
        grace = (
            self.config.health_check_period_s * self.config.health_check_failure_threshold
            + 10.0
        )
        down_since = None
        while not self._shutdown.is_set():
            await asyncio.sleep(0.2)
            if not self.head.closed:
                down_since = None
                continue
            now = asyncio.get_running_loop().time()
            if down_since is None:
                down_since = now
            elif now - down_since > grace:
                self._shutdown.set()
                return
            conn = None
            try:
                from ..util.aio import dial  # lazy: util/__init__ → core

                # walk the head ring: after a failover the successor standby
                # answers on a different addr than the dead active
                addr = self._head_ring.current or self.head_addr
                netchaos.register_addr(addr, "n0")
                conn = await dial(
                    addr, purpose="head",
                    timeout=self.config.dial_timeout_s, peer_node="n0",
                )
                conn.set_push_handler(self._on_head_push)
                fields = {
                    # local grants kept flowing while the head was down; the
                    # block snapshot lets the restarted head re-adopt the
                    # delegation (and reconcile grants made in the outage)
                    "lease_blocks": self.granter.block_snapshot(),
                    "metrics_addr": self.metrics_addr,
                }
                if self.incarnation is not None:
                    # our token travels with the re-register: a head that
                    # declared this incarnation dead refuses with
                    # FencedError instead of silently re-adopting a zombie
                    fields["ninc"] = self.incarnation
                reg_reply = await conn.call(
                    "register",
                    role="agent",
                    client_id=self.node_id,
                    addr=self.serve_addr,
                    resources=self.resources,
                    labels=self.labels,
                    pid=os.getpid(),
                    timeout=5,
                    **fields,
                )
                offered = reg_reply.get("head_epoch")
                if (offered is not None and self.head_epoch
                        and int(offered) < self.head_epoch):
                    # a resurrected OLD head answered here: re-anchoring to
                    # it would split the cluster — rotate toward the
                    # successor instead (the zombie demotes on its own once
                    # it sees the higher epoch on stamped traffic)
                    from ..util import flightrec

                    if flightrec.REC is not None:
                        flightrec.REC.record(
                            "ha", "ha_fence_old_head",
                            method="register", offered=int(offered),
                            known=self.head_epoch,
                        )
                    await conn.close()
                    self._head_ring.rotate()
                    continue
                # the restarted head has no delta state for this node: the
                # next node_sync must be a full resync.  Reset BEFORE
                # adopting the connection so a failure here still closes
                # `conn` below instead of stranding a half-registered head.
                self.reporter.reset()
                self._adopt_register_reply(reg_reply)
                self.head = conn
                # _watch_head is the sole writer of head_addr; `addr` is the
                # ring slot THIS register round-trip succeeded against, so a
                # concurrent ring merge must not retarget the assignment:
                # ca-lint: ignore[async-await-race]
                self.head_addr = addr
                down_since = None
            except asyncio.CancelledError:
                if conn is not None:
                    await conn.close()
                raise  # agent shutdown beats head-watching
            except FencedError:
                # death verdict discovered at re-register (partition healed):
                # purge everything minted under the dead incarnation, then
                # let the next loop iteration rejoin fresh
                if conn is not None:
                    await conn.close()
                await self._fence_reset()
                down_since = asyncio.get_running_loop().time()  # fresh grace
            except Exception:
                if conn is not None:
                    # registering failed: a leaked half-open socket per retry
                    # tick adds up fast while the head flaps
                    await conn.close()
                # this candidate is dead or refusing: try the next head in
                # the ring on the following attempt (single-head rings are a
                # no-op rotate)
                self._head_ring.rotate()
                # jittered: N agents redialing a restarted head must not
                # arrive as one synchronized thundering herd
                await asyncio.sleep(0.3 + random.random() * 0.4)

    def _teardown(self):
        import shutil

        if self._http_server is not None:
            try:
                self._http_server.close()
            except Exception:
                pass
        for wid in list(self.procs):
            self._kill_worker(wid)
        shutil.rmtree(self.shm_ns_dir, ignore_errors=True)

    def main(self):
        loop = asyncio.new_event_loop()
        if hasattr(asyncio, "eager_task_factory"):
            loop.set_task_factory(asyncio.eager_task_factory)
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._amain())
        except (KeyboardInterrupt, SystemExit):
            self._teardown()


def main():
    NodeAgent().main()


if __name__ == "__main__":
    main()
