"""ObjectRef / DeviceRef handles.

ObjectRef is the future-like handle returned by `put()` and `.remote()`.
Mirrors the reference's ObjectRef (python/ray/includes/object_ref.pxi) incl.
refcount notification on destruction so the owner can GC shared-memory data.

DeviceRef is the TPU-native extension: a handle to a sharded `jax.Array` (or a
pytree of them) that lives on TPU inside the owning actor's process and is
never copied to host when passed back into that actor's methods.  `get()`ing a
DeviceRef outside the owning process materializes it to host explicitly — the
framework refuses to do that silently for arrays above a threshold unless
`allow_device_fetch` is set, because implicit device->host copies are the #1
TPU performance foot-gun.
"""

from __future__ import annotations

from typing import Any, Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "_worker", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None, worker=None):
        self.id = object_id
        self.owner = owner  # client id of the owning process
        self._worker = worker
        if worker is not None:
            if owner is not None:
                # ownership plane: remember who settles this ref's counts so
                # inc/dec route to the owner's ledger, not the head
                worker.note_borrowed_owner(self.id.binary(), owner)
            if worker.reference_counter.add_local_ref(self.id) == 1:
                # a handle came back for an object whose local refs all died
                # (e.g. returned from an actor): its producing task's lineage
                # must no longer count it dead, or the spec could be dropped
                # while this ref still needs it for reconstruction
                worker.lineage_revive(self.id)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from .worker import global_worker

        return global_worker().resolve_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __del__(self):
        w = self._worker
        if w is not None:
            try:
                w.reference_counter.remove_local_ref(self.id)
            except Exception:
                pass

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Serialized refs travel through task specs; the receiving process
        # reconstructs a handle registered with its local worker so borrowed
        # references are counted.  The sender additionally captures every
        # nested ref it pickles (serialization.ref_capture) and pins it at
        # the head until the receiver's own registration lands — without the
        # pin, the sender dropping its handle mid-transit would let the head
        # GC an object the receiver is about to use (reference_count.h
        # borrowing protocol, centralized-ownership form).
        from .serialization import note_serialized_ref

        note_serialized_ref(self.id.binary())
        return (_rehydrate_ref, (type(self).__name__, self.id.binary(), self.owner))


class DeviceRef(ObjectRef):
    __slots__ = ("spec",)

    def __init__(self, object_id, owner=None, worker=None, spec: Any = None):
        super().__init__(object_id, owner, worker)
        # spec: lightweight description (shapes/dtypes/sharding) for display
        # and for shape-checking without touching the device data.
        self.spec = spec

    def __repr__(self):
        return f"DeviceRef({self.id.hex()}, owner={self.owner}, spec={self.spec})"

    def __reduce__(self):
        from .serialization import note_serialized_ref

        note_serialized_ref(self.id.binary())
        return (_rehydrate_device_ref, (self.id.binary(), self.owner, self.spec))


def _rehydrate_ref(kind: str, id_bytes: bytes, owner):
    from .worker import try_global_worker

    w = try_global_worker()
    return ObjectRef(ObjectID(id_bytes), owner, w)


def _rehydrate_device_ref(id_bytes: bytes, owner, spec):
    from .worker import try_global_worker

    w = try_global_worker()
    return DeviceRef(ObjectID(id_bytes), owner, w, spec)
