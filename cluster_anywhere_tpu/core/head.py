"""Head control plane ("GCS" analogue).

One process per cluster.  Owns cluster metadata and cluster-wide decisions,
mirroring the subsystem split of the reference's GCS server
(src/ray/gcs/gcs_server/gcs_server.h): node table with joins/deaths
(gcs_node_manager.h), worker tables, per-node worker pools, resource
accounting + lease scheduler with pluggable policies (scheduling.py),
actor directory with restart FSM, placement groups with multi-node bundle
placement, namespaced KV, pubsub, object directory with locations + refcount
GC, and health checking.  Workers and drivers talk to it over the msgpack
protocol (protocol.py: unix sockets same-host, TCP across hosts); the hot
task path does NOT go through the head — drivers lease workers and push tasks
directly (normal_task_submitter.h lease model).

Multi-node topology: the head embeds the local node ("n0": it spawns and
monitors that node's workers directly, and serves that node's object pulls).
Every other node runs a node agent (nodeagent.py, the raylet analogue) that
registers here over TCP, spawns workers on head request, reports their
deaths, and serves chunked object pulls from its node's shm namespace.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import signal
import subprocess
import sys
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import netchaos, scheduling
from .config import CAConfig
from .errors import (
    ActorDiedError,
    FencedError,
    ObjectStoreFullError,
    PlacementGroupError,
)
from .protocol import (
    Connection,
    Server,
    fence_close,
    fence_close_conn,
    spawn_bg,
    write_frame,
)

LOCAL_NODE = "n0"

# Lease plane: pools whose unit-shape lease class is delegatable to node
# agents, and the resource shape ONE delegated slot backs.  Only the hot
# default class ({"CPU": 1}, no PG, no strategy) moves off the head; PG
# leases, custom shapes, and placement strategies always grant centrally so
# every bundle-charging / policy invariant stays in one place.
LEASE_UNIT_SHAPES = {"cpu": {"CPU": 1.0}}

# --------------------------------------------------------------------------
# state records
# --------------------------------------------------------------------------


@dataclass
class NodeRec:
    node_id: str
    addr: Optional[str]  # agent RPC address; None = head-embedded local node
    total: Dict[str, float]
    avail: Dict[str, float]
    index: int = 0  # join order (scheduling tiebreak: pack onto earliest)
    # drain-plane FSM: alive -> draining -> drained | dead.  A draining node
    # is still UP (accounting, pulls, heartbeats) but no longer SCHEDULABLE
    # (grants, delegation, PG placement, actor placement all skip it).
    state: str = "alive"  # alive | draining | drained | dead
    drain_reason: str = ""  # preemption | idle | manual (while draining/drained)
    drain_deadline: float = 0.0  # monotonic deadline for the evacuation window
    # fencing token, minted at register and bumped on every rejoin after a
    # death verdict: authority-bearing RPCs stamped with an older value are
    # refused with FencedError (partition tolerance — a node the head
    # declared dead must not keep acting out of its pre-verdict state)
    incarnation: int = 1
    pid: int = 0
    last_heartbeat: float = field(default_factory=time.monotonic)
    idle: Dict[str, deque] = field(default_factory=lambda: {"cpu": deque(), "tpu": deque()})
    conn: Optional[Connection] = None  # head -> agent connection
    max_workers: int = 64
    mem_pressured: bool = False  # agent-reported memory pressure (monitor)
    load: Dict[str, float] = field(default_factory=dict)  # heartbeat telemetry
    labels: Dict[str, str] = field(default_factory=dict)  # static node labels
    # lease plane: workers whose unit-shape lease capacity is delegated to
    # this node's agent (pool -> set of wids).  Their shape is pre-charged
    # against avail, so agent-side grants need no head accounting.
    delegated: Dict[str, set] = field(default_factory=dict)
    # agent-reported block occupancy/counters, disseminated via heartbeats
    lease_used: Dict[str, dict] = field(default_factory=dict)
    # last node_sync delta version applied (delta-synced node state)
    sync_version: int = 0
    # metrics plane: the agent's HTTP scrape endpoint (Prometheus dials it
    # directly; `ca metrics --node` resolves through here when the head is up)
    metrics_addr: Optional[str] = None

    @property
    def is_local(self) -> bool:
        return self.addr is None

    @property
    def up(self) -> bool:
        """Node process is running (accounting/pulls valid) — includes
        draining nodes, which are up but not schedulable."""
        return self.state in ("alive", "draining")


@dataclass
class WorkerRec:
    worker_id: str
    pid: int
    addr: str  # address it serves (unix: same host, tcp: other nodes)
    node_id: str = LOCAL_NODE
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"  # starting | idle | leased | actor | dead
    purpose: str = "pool"  # pool | actor — actor workers never join the idle pool
    pool: str = "cpu"  # cpu | tpu — tpu workers keep the accelerator runtime env
    lease_id: Optional[str] = None
    actor_id: Optional[str] = None
    last_heartbeat: float = field(default_factory=time.monotonic)
    blocked: bool = False  # blocked in get(); its cpus are released
    busy_since: float = 0.0  # monotonic time the current lease/actor began
    tpu_chip: Optional[int] = None  # pinned chip id (multi-chip hosts only)
    addr_tcp: Optional[str] = None  # TCP dual of addr, for remote clients


@dataclass
class ActorRec:
    actor_id: str
    name: Optional[str]
    fn_id: bytes
    init_spec: bytes  # packed (args, kwargs, options)
    resources: Dict[str, float]
    max_restarts: int
    restarts_used: int = 0
    incarnation: int = 0
    state: str = "pending"  # pending | alive | restarting | dead
    worker_id: Optional[str] = None
    addr: Optional[str] = None
    detached: bool = False
    max_concurrency: int = 1
    concurrency_groups: Optional[dict] = None
    method_options: Optional[dict] = None  # method name -> @method(**opts)
    death_cause: str = ""
    pg_id: Optional[str] = None
    bundle_index: int = -1
    runtime_env: Optional[dict] = None
    strategy: Optional[dict] = None  # scheduling strategy wire dict
    node_id: Optional[str] = None  # where this incarnation runs
    # drain hook: False opts this actor out of automatic drain migration —
    # a supervisor (e.g. the serve controller) owns its lifecycle and drains
    # it application-aware (replacements first, in-flight streams finish)
    # instead of the head's restart-FSM migration killing it mid-request
    drain_migration: bool = True
    # where this incarnation's resources are currently charged:
    # "pg" (bundle.used) | "node" (node.avail) | None (not charged) — guards
    # against double-crediting when a PG is removed before the actor's
    # worker-death event is processed
    charged: Optional[str] = None

    @property
    def can_restart(self) -> bool:
        """Restart budget remains (max_restarts=-1 means unlimited)."""
        return self.max_restarts != 0 and (
            self.max_restarts < 0 or self.restarts_used < self.max_restarts
        )


@dataclass
class ObjectRec:
    oid: bytes
    shm_name: Optional[str]
    size: int
    owner: str  # client id of owner process
    node_id: str = LOCAL_NODE  # node holding the primary copy
    copies: Dict[str, str] = field(default_factory=dict)  # node_id -> shm_name
    holders: set = field(default_factory=set)  # client ids holding refs
    owner_released: bool = False
    # oids of ObjectRefs serialized inside this object's payload: they are
    # held alive (holder "cnt:<oid>") for as long as this object exists
    # (borrowed-reference containment edges)
    contains: List[bytes] = field(default_factory=list)
    # ownership-plane form of the same, for containers whose owner has no
    # ledger (client mode): [oid, authority-cid-or-""] pairs whose edges
    # live at each inner object's OWN authority — released by the registry
    # when this record settles (see _release_cnt_pairs)
    cnt_pairs: Optional[list] = None
    # spill state (external_storage.py analogue): when set, the bytes live in
    # a disk file on `node_id`; pending_free is the old shm slice awaiting
    # reclaim until the last zero-copy pin drops
    spill_path: Optional[str] = None
    pending_free: Optional[str] = None


@dataclass
class LeaseReq:
    shape: Dict[str, float]
    reply: Any
    reply_err: Any
    client: str
    pg_id: Optional[str] = None
    bundle_index: int = -1
    strategy: Optional[dict] = None
    remote: bool = False  # requester is a remote client: hand out TCP addrs
    # expiry deadline for lease-plane escalation probes: a submitter that can
    # also be served by agents' delegated blocks marks its head request with a
    # ttl; the head answers {"expired": True} past the deadline instead of
    # holding it pending — so delegatable-class overflow never pins central
    # capacity reclamation (the submitter re-probes the agents and
    # re-subscribes).  None = classic request, held until grantable.
    deadline: Optional[float] = None


@dataclass
class BundleRec:
    resources: Dict[str, float]
    used: Dict[str, float] = field(default_factory=dict)
    node_id: Optional[str] = None  # assigned node (None until placed)
    labels: Optional[dict] = None  # hard label selector constraining placement


@dataclass
class PGRec:
    pg_id: str
    bundles: List[BundleRec]
    strategy: str
    state: str = "created"  # "pending" until all bundles placed, then "created"


# --------------------------------------------------------------------------


class Head:
    def __init__(self, session_dir: str, config: CAConfig, resources: Dict[str, float]):
        self.session_dir = session_dir
        self.session_name = os.path.basename(session_dir)
        self.config = config
        self.sock_path = os.path.join(session_dir, "head.sock")
        self.tcp_addr: Optional[str] = None  # filled after server start
        # -- node table (gcs_node_manager.h analogue); the head embeds n0 --
        self.nodes: Dict[str, NodeRec] = {}
        self._node_index = 0
        from .accelerators import detect_node_labels

        self._add_node(
            NodeRec(
                LOCAL_NODE, None, dict(resources), dict(resources),
                labels=detect_node_labels(LOCAL_NODE),
            )
        )
        # chip allocator for TPU-worker pinning; active only on multi-chip
        # hosts (a single chip needs no TPU_VISIBLE_CHIPS restriction)
        n_chips = int(resources.get("TPU", 0))
        self._chip_alloc = None
        if n_chips > 1:
            from .accelerators import ChipAllocator

            self._chip_alloc = ChipAllocator(n_chips)
        # highest incarnation ever minted per node id (snapshot-persisted):
        # a rejoining node always gets a strictly larger token than any
        # verdict it may have zombied through
        self._node_incarnations: Dict[str, int] = {LOCAL_NODE: 1}
        # network-chaos plane: the spec last broadcast via `net_chaos` (new
        # registrants receive it in their register reply).  The epoch
        # travels WITH it everywhere: a spec re-anchored at each receiver's
        # install time would re-open already-healed windows (observed: a
        # healed agent re-partitioning itself out of its register reply).
        self._net_chaos_spec = ""
        self._net_chaos_epoch: Optional[float] = None
        netchaos.maybe_install_from_config(config, LOCAL_NODE)
        # -- tables --
        self.workers: Dict[str, WorkerRec] = {}
        self.actors: Dict[str, ActorRec] = {}
        self.named_actors: Dict[str, str] = {}
        self.objects: Dict[bytes, ObjectRec] = {}
        # refs reported before obj_created arrived (cross-socket ordering).
        # Bounded by an EXPLICIT grace window (config.early_ref_grace_s, the
        # same bound owner ledgers use for their pending adds): entries older
        # than the window are swept by the monitor loop instead of relying on
        # the obj_created eventually arriving — a crashed producer must not
        # pin its early refs forever.
        self._early_refs: Dict[bytes, set] = {}
        self._early_ref_ts: Dict[bytes, float] = {}
        # ownership plane: per-owner ledger digests (owner_sync deltas).
        # The head is the failover arbiter — when an owner dies, the last
        # synced digest is what it adopts (borrower sets + released flags)
        # so orphaned objects drain through the central path without leaking
        # shm segments or spill files.
        self.owner_digests: Dict[str, Dict[bytes, dict]] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}
        self.pgs: Dict[str, PGRec] = {}
        self.pending_pgs: deque = deque()  # PG ids awaiting resources, FIFO
        self._pg_waiters: Dict[str, List[asyncio.Future]] = {}
        self.pending_leases: deque[LeaseReq] = deque()
        self.leases: Dict[str, str] = {}  # lease_id -> worker_id
        self._lease_shapes: Dict[str, Dict[str, float]] = {}
        self._lease_pg: Dict[str, tuple] = {}  # lease_id -> (pg_id, bundle_index)
        self._lease_node: Dict[str, str] = {}  # lease_id -> node_id
        self._lease_client: Dict[str, str] = {}  # lease_id -> holder client_id
        self._last_reclaim_nudge = 0.0  # debounce for lease_reclaim pushes
        self._spawn_count = 0
        # -- conns --
        self._worker_conns: Dict[str, Connection] = {}
        self._clients: Dict[str, dict] = {}  # client_id -> conn state
        self._register_waiters: Dict[str, asyncio.Future] = {}
        self.subscribers: Dict[str, List[Any]] = {}  # channel -> [writer]
        # --- HA plane (warm-standby replication / epoch-fenced authority) --
        # role FSM: standby --promote--> active --observe higher epoch-->
        # demoted.  A standby holds the replicated cluster state in memory
        # (self._ha_shadow, fed by the active head's replication stream) and
        # serves only ha_status/head_promote until it promotes; a demoted
        # head refuses everything, releases its sockets, and exits.
        self.ha_role = "standby" if os.environ.get("CA_HEAD_STANDBY") else "active"
        self.ha_rank = int(os.environ.get("CA_HEAD_STANDBY_RANK", "0") or 0)
        # monotonic authority epoch, minted at promotion and persisted next
        # to the node-incarnation table: PR 15's "which head is
        # authoritative for this node" generalized to "which head is
        # authoritative, period".  Stamped (`hep`) on authority-bearing
        # traffic exactly like node incarnations (`ninc`).
        self.head_epoch = 1
        self._ha_observed_epoch = 0  # highest successor epoch seen (demoted)
        self._ha_restored_addr: Optional[str] = None  # own addr from snapshot
        self._repl_seq = 0
        self._repl_dirty = False
        self._repl_log: deque = deque(
            maxlen=int(getattr(config, "ha_repl_log_max", 4096))
        )
        self._repl_subs: Dict[str, dict] = {}  # standby client_id -> sub
        self._repl_table_digests: Dict[str, int] = {}
        self._repl_last_lag_event = 0.0
        # standby-side stream/apply state
        self._ha_shadow: Optional[dict] = None
        self._ha_watermark = 0
        self._ha_active_conn = None
        self._ha_active_addr: Optional[str] = None
        self._ha_last_rx = 0.0
        self._ha_loops_started = False
        self._ha_tasks: List[Any] = []
        self._ha_replog = None
        self._sock_server: Optional[Server] = None
        self.stats = {
            "leases_granted": 0,
            "tasks_pushed": 0,
            "actors_created": 0,
            "actor_restarts": 0,
            "objects_created": 0,
            "objects_gc": 0,
            "workers_spawned": 0,
            "nodes_joined": 0,
            "nodes_died": 0,
            "objects_transferred": 0,
            "oom_kills": 0,
            "lease_blocks_delegated": 0,  # worker-slots handed to agents
            "lease_blocks_returned": 0,  # slots revoked/returned to the head
            # drain plane (per-reason drain_nodes_<reason> keys appear lazily)
            "nodes_drained": 0,  # drains completed (node reached `drained`)
            "drain_actors_migrated": 0,  # actors proactively restarted off a draining node
            "drain_objects_migrated": 0,  # sole-copy primaries re-homed to survivors
            "drain_deadline_kills": 0,  # busy workers killed at the drain deadline
        }
        # draining nodes whose background evacuation pass has finished (the
        # quiesce check refuses to finalize before actors/objects are out)
        self._drain_evac_done: set = set()
        self._last_deleg_reclaim = 0.0  # debounce for block revocations
        # (node_id, wid) -> pool: block workers an agent reported that the
        # head didn't know yet (snapshotless restart, agent registered before
        # its workers).  Their re-registration adopts them straight into the
        # delegated state instead of the central idle pool — without this the
        # same worker would be grantable by BOTH planes.
        self._pending_block_adopt: Dict[Tuple[str, str], str] = {}
        # last time CENTRAL-only work (no-ttl leases, PGs) was queued:
        # delegation holds off until demand has been quiet for a beat, so
        # wave-shaped central floods (SPREAD bursts) don't lose capacity to
        # the lease blocks between waves
        self._last_central_demand = 0.0
        # per-method RPC counters (saturation diagnostics: the owner-based
        # directory and p2p collectives exist to keep hot-path traffic OFF
        # this loop — these counters are how tests/benchmarks prove it)
        from collections import defaultdict

        self.rpc_counts: Dict[str, int] = defaultdict(int)
        # p2p directory: client_id -> {addr, addr_tcp, node} for every
        # registered client that serves RPCs (workers AND drivers).  Lets a
        # borrower dial an object's owner directly (owner_locate) instead of
        # polling this loop.
        self.client_addrs: Dict[str, Dict[str, str]] = {}
        # node memory monitor (memory_monitor.h:52): the head watches its own
        # node; agents report pressure in heartbeats and the head picks the
        # victim (worker_killing_policy.h) since only it knows worker state
        self.mem_monitor = None
        if config.memory_monitor_refresh_ms > 0 and config.memory_usage_threshold > 0:
            from .memory_monitor import MemoryMonitor

            self.mem_monitor = MemoryMonitor(config.memory_usage_threshold)
        self._last_mem_check = 0.0
        self._last_dir_touch = 0.0
        self._shutdown = asyncio.Event()
        self._driver_clients: set = set()
        # observability: task-event ring buffer (GcsTaskManager analogue) and
        # aggregated user metrics (MetricsAgent analogue)
        self.task_events: deque = deque(maxlen=50_000)
        self.metrics: Dict[str, dict] = {}  # name -> {type, desc, data{tags_key: ...}}
        # flight recorder: cluster-merged journal of plane decision events.
        # Worker/agent slices arrive piggybacked on metrics_report /
        # node_sync; head-origin decisions mirror in via _log_event and the
        # head's own recorder (netchaos etc. running in this process).
        self.flightrec: deque = deque(
            maxlen=int(getattr(config, "flightrec_head_len", 50_000))
        )
        self._flightrec_on = bool(getattr(config, "flightrec_plane", True))
        if self._flightrec_on:
            from ..util import flightrec as _flightrec

            _flightrec.init(
                cap=int(getattr(config, "flightrec_ring_len", 4096)),
                node_id=LOCAL_NODE, proc="head",
            )
        # metrics plane: time-series retention (ring buffers, two downsample
        # tiers) sampled off this table + head stats by the monitor loop, so
        # dashboards/`ca top` get rates and history without Prometheus
        from ..util.timeseries import TimeSeriesStore

        ts_len = int(getattr(config, "timeseries_len", 360))
        ts_int = float(getattr(config, "timeseries_interval_s", 10.0))
        self.timeseries = None
        if ts_int > 0:
            self.timeseries = TimeSeriesStore(
                tiers=(
                    (ts_int, ts_len),
                    (ts_int * int(getattr(config, "timeseries_tier1_mult", 12)), ts_len),
                ),
                max_series=int(getattr(config, "timeseries_max_series", 1024)),
            )
        self._last_ts_sample = 0.0
        # head self-instrumentation: per-RPC-type dispatch latency and
        # inflight-handler histograms + an event-loop lag gauge, written
        # straight into the metrics table (this process has no flusher —
        # it IS the aggregator).  These series are how the dispatch
        # saturation knee (SCALE.md "Head saturation") becomes measurable
        # instead of inferred.
        self._dispatch_inflight = 0
        self._self_tags_keys: Dict[str, str] = {}  # method -> cached tags_key
        # log plane: drivers subscribed to the cluster log stream (log_sub);
        # agents' log_batch notifies and the local-node tailer fan out here.
        # Bounded by drop-not-backpressure: a subscriber whose socket buffer
        # is full loses the batch (counted), workers never block on logs.
        self._log_subs: Dict[str, Any] = {}  # client_id -> writer
        self.stats["log_lines_shipped"] = 0
        self.stats["log_lines_dropped"] = 0
        if config.log_capture:
            # the head captures its own output the same way workers do
            # (nodes/n0/head.jsonl rides the local tail loop)
            try:
                from ..util.logplane import install_capture

                install_capture(
                    session_dir, LOCAL_NODE, "head",
                    max_bytes=config.log_rotate_bytes,
                )
            except Exception:
                pass
        # structured lifecycle event log (util/event.h analogue): JSONL file
        self._event_log = open(os.path.join(session_dir, "events.jsonl"), "a", buffering=1)
        # transit tokens acked by the receiver BEFORE the sender's pin landed
        # (the two travel on different sockets): tombstones cancel the late
        # pin instead of leaking a permanent holder
        self._spent_transit: Dict[str, float] = {}
        # live transit pins: token -> (created_at, pinned oids).  Normally
        # released by the receiver's transit_done; the TTL sweep reclaims
        # pins whose reply was lost in flight (e.g. the borrower's RPC timed
        # out after the owner had already pinned and replied) — without it
        # such a pin would hold the objects for the owner's whole lifetime
        self._transit_pins: Dict[str, Tuple[float, List[bytes]]] = {}
        # tombstones of disconnected client ids (drivers/workers): lets
        # client_addr answer "dead", which borrowers use to fail fast with
        # ObjectLostError instead of polling a dead owner to their timeout
        # (OwnerDiedError role).  Bounded FIFO.
        self._departed_clients: "OrderedDict[str, None]" = OrderedDict()
        # fault tolerance (gcs_server.h StorageType analogue, file-backed):
        # debounced snapshots of the cluster tables; a restarted head loads
        # them and re-adopts live workers/agents/drivers
        self._ckpt_path = os.environ.get("CA_HEAD_CKPT") or os.path.join(
            session_dir, "head.ckpt"
        )
        self._dirty = False
        self._restored = False
        # torn-snapshot tolerance: head.ckpt is written via tmp+rename and
        # rotated to .bak first, so a corrupt/missing primary (kill -9 inside
        # _save_snapshot, disk fault) falls back to the previous good one.
        # Standbys skip this — their state comes from the replication stream
        # (plus their own journal), never from the active head's snapshot.
        if self.ha_role == "active":
            for path in (self._ckpt_path, self._ckpt_path + ".bak"):
                if not os.path.exists(path):
                    continue
                try:
                    self._load_snapshot(path)
                    self._restored = True
                    if path != self._ckpt_path:
                        self._log_event("snapshot_fallback_bak", path=path)
                    break
                except Exception as e:
                    self._log_event(
                        "snapshot_load_failed", path=path, error=repr(e)
                    )
        # pull-side file maps for serving n0's object chunks
        self._pull_maps: Dict[str, Any] = {}
        # listener — constructed AFTER the snapshot load so a restored
        # `ha.tcp_addr` can pin the port.  An active head rebinds the SAME
        # tcp port (agents/remote workers reconnect to the address they were
        # given), preferring its own persisted addr over the head.addr file,
        # which a successor head may have claimed since (failover); a
        # standby binds an ephemeral port and its own rank-suffixed socket.
        host = getattr(config, "head_host", "127.0.0.1")
        port = 0
        # deferred-socket restart: when head.addr names a DIFFERENT head than
        # the one this snapshot belonged to, a successor may own the session
        # unix socket — don't bind (or unlink!) head.sock until the boot
        # probe proves this head is still authoritative
        self._ha_sock_deferred = False
        if self.ha_role == "active":
            cur = ""
            try:
                cur = open(os.path.join(session_dir, "head.addr")).read().strip()
            except OSError:
                pass
            prev = self._ha_restored_addr or cur
            if prev.startswith("tcp:"):
                try:
                    port = int(prev.rpartition(":")[2])
                except ValueError:
                    port = 0
            if (
                self._restored and cur and prev and cur != prev
                and bool(getattr(config, "ha_boot_probe", True))
            ):
                self._ha_sock_deferred = True
        else:
            self.sock_path = os.path.join(
                session_dir, f"head.standby{self.ha_rank}.sock"
            )
        addrs = (
            [f"tcp:{host}:{port}"]
            if self._ha_sock_deferred
            else [self.sock_path, f"tcp:{host}:{port}"]
        )
        self.server = Server(addrs, self._handle, self._on_disconnect)

    def _add_node(self, node: NodeRec) -> NodeRec:
        node.index = self._node_index
        self._node_index += 1
        node.max_workers = int(node.total.get("CPU", 4)) * 4 + 4
        self.nodes[node.node_id] = node
        return node

    @property
    def local_node(self) -> NodeRec:
        return self.nodes[LOCAL_NODE]

    def _alive_nodes(self) -> List[NodeRec]:
        """SCHEDULABLE nodes: draining nodes are excluded — nothing new is
        placed on capacity that is announced to be leaving."""
        return [n for n in self.nodes.values() if n.state == "alive"]

    def _up_nodes(self) -> List[NodeRec]:
        return [n for n in self.nodes.values() if n.up]

    def _node_views(self, nodes: Optional[List[NodeRec]] = None) -> List[scheduling.NodeView]:
        return [
            scheduling.NodeView(n.node_id, n.total, n.avail, n.index, labels=n.labels)
            for n in (nodes if nodes is not None else self._alive_nodes())
        ]

    def _agg_total(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._alive_nodes():
            for k, v in n.total.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def _agg_avail(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._alive_nodes():
            for k, v in n.avail.items():
                out[k] = out.get(k, 0.0) + v
        return out

    # ------------------------------------------------------ fault tolerance
    def _snapshot_state(self) -> dict:
        """The cluster tables as one plain dict — the unit of persistence
        (snapshot file) AND of replication (full transfers / table deltas to
        warm standbys all serialize the same tables)."""
        state = {
            "nodes": [
                {
                    "node_id": n.node_id, "addr": n.addr, "total": n.total,
                    "avail": n.avail, "index": n.index, "state": n.state,
                    "pid": n.pid, "labels": n.labels,
                    "incarnation": n.incarnation,
                    "drain_reason": n.drain_reason,
                    # monotonic deadlines don't survive a restart: persist
                    # the remaining window and re-anchor it at load
                    "drain_in": (
                        max(0.0, n.drain_deadline - time.monotonic())
                        if n.state == "draining"
                        else 0.0
                    ),
                    # delegated lease blocks survive a head restart: avail
                    # already carries their unit charges, so membership must
                    # be restored with it or the accounting desyncs
                    "delegated": {p: sorted(w) for p, w in n.delegated.items() if w},
                }
                for n in self.nodes.values()
            ],
            "node_index": self._node_index,
            "workers": [
                {
                    "worker_id": w.worker_id, "pid": w.pid, "addr": w.addr,
                    "addr_tcp": w.addr_tcp,
                    "node_id": w.node_id, "state": w.state, "purpose": w.purpose,
                    "pool": w.pool, "lease_id": w.lease_id, "actor_id": w.actor_id,
                }
                for w in self.workers.values()
                if w.state != "dead"
            ],
            "spawn_count": self._spawn_count,
            "actors": [
                {
                    "actor_id": a.actor_id, "name": a.name, "fn_id": a.fn_id,
                    "init_spec": a.init_spec, "resources": a.resources,
                    "max_restarts": a.max_restarts, "restarts_used": a.restarts_used,
                    "incarnation": a.incarnation, "state": a.state,
                    "worker_id": a.worker_id, "addr": a.addr, "detached": a.detached,
                    "max_concurrency": a.max_concurrency,
                    "concurrency_groups": a.concurrency_groups,
                    "method_options": a.method_options,
                    "death_cause": a.death_cause,
                    "pg_id": a.pg_id, "bundle_index": a.bundle_index,
                    "runtime_env": a.runtime_env, "strategy": a.strategy,
                    "node_id": a.node_id, "charged": a.charged,
                    "drain_migration": a.drain_migration,
                }
                for a in self.actors.values()
            ],
            "named_actors": self.named_actors,
            "departed_clients": list(self._departed_clients),
            "kv": self.kv,
            "pgs": [
                {
                    "pg_id": p.pg_id, "strategy": p.strategy, "state": p.state,
                    "bundles": [
                        {
                            "resources": b.resources, "used": b.used,
                            "node_id": b.node_id, "labels": b.labels,
                        }
                        for b in p.bundles
                    ],
                }
                for p in self.pgs.values()
            ],
            "pending_pgs": list(self.pending_pgs),
            "node_incarnations": self._node_incarnations,
            "objects": [
                {
                    "oid": r.oid, "shm_name": r.shm_name, "size": r.size,
                    "owner": r.owner, "node_id": r.node_id, "copies": r.copies,
                    "holders": list(r.holders), "owner_released": r.owner_released,
                    "contains": r.contains, "cnt_pairs": r.cnt_pairs,
                    "spill_path": r.spill_path,
                    "pending_free": r.pending_free,
                }
                for r in self.objects.values()
            ],
            "leases": self.leases,
            "lease_shapes": self._lease_shapes,
            "lease_pg": {k: list(v) for k, v in self._lease_pg.items()},
            "lease_node": self._lease_node,
            "stats": self.stats,
            # ownership plane: owners whose death lands in the restart
            # window must still be adoptable from their last synced digest
            "owner_digests": [
                [cid, [[oid, info] for oid, info in d.items()]]
                for cid, d in self.owner_digests.items()
            ],
            # HA plane: the authority epoch rides the snapshot next to the
            # node-incarnation table, plus our own tcp addr so a restarted
            # head rebinds ITS port (not a successor's from head.addr)
            "ha": {
                "epoch": self.head_epoch,
                "tcp_addr": self.tcp_addr or self._ha_restored_addr or "",
            },
        }
        return state

    def _save_snapshot(self):
        """Atomically persist the cluster tables (kill -9 of the head must
        not lose actors/PGs/KV/object locations; gcs_table_storage.h role)."""
        import msgpack

        blob = msgpack.packb(self._snapshot_state(), use_bin_type=True)
        tmp = self._ckpt_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        # keep the previous snapshot as .bak before the atomic swap: a head
        # killed mid-save leaves at worst a torn .tmp (ignored) — and even a
        # torn/corrupted head.ckpt (operator error, disk fault) still
        # restarts from the last good state instead of empty tables
        try:
            os.replace(self._ckpt_path, self._ckpt_path + ".bak")
        except FileNotFoundError:
            pass
        os.replace(tmp, self._ckpt_path)

    def _load_snapshot(self, path: Optional[str] = None):
        import msgpack

        with open(path or self._ckpt_path, "rb") as f:
            state = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
        self._load_state(state)

    def _load_state(self, state: dict):
        """Adopt a full cluster-state dict (_snapshot_state schema) — shared
        by snapshot restore and standby promotion (the replicated shadow)."""
        now = time.monotonic()
        for cid in state.get("departed_clients") or []:
            self._departed_clients[cid] = None
        self.nodes = {}
        for n in state["nodes"]:
            rec = NodeRec(
                n["node_id"], n["addr"], n["total"], n["avail"],
                index=n["index"], state=n["state"], pid=n["pid"],
                labels=n.get("labels") or {},
                incarnation=int(n.get("incarnation") or 1),
            )
            rec.drain_reason = n.get("drain_reason") or ""
            if rec.state == "draining":
                rec.drain_deadline = now + float(n.get("drain_in") or 0.0)
            rec.delegated = {
                p: set(w) for p, w in (n.get("delegated") or {}).items()
            }
            rec.max_workers = int(rec.total.get("CPU", 4)) * 4 + 4
            rec.last_heartbeat = now  # grace: agents get time to reconnect
            self.nodes[rec.node_id] = rec
        self._node_index = state["node_index"]
        self._spawn_count = state["spawn_count"]
        for w in state["workers"]:
            rec = WorkerRec(
                w["worker_id"], w["pid"], w["addr"], node_id=w["node_id"],
                purpose=w["purpose"], pool=w["pool"],
            )
            rec.addr_tcp = w.get("addr_tcp")
            rec.state = w["state"]
            rec.lease_id = w["lease_id"]
            rec.actor_id = w["actor_id"]
            rec.last_heartbeat = now
            self.workers[rec.worker_id] = rec
            if rec.state == "idle":
                node = self.nodes.get(rec.node_id)
                if node is not None and node.state == "alive":
                    node.idle[rec.pool].append(rec.worker_id)
        for a in state["actors"]:
            self.actors[a["actor_id"]] = ActorRec(**a)
        self.named_actors = state["named_actors"]
        self.kv = state["kv"]
        for p in state["pgs"]:
            self.pgs[p["pg_id"]] = PGRec(
                pg_id=p["pg_id"], strategy=p["strategy"], state=p["state"],
                bundles=[BundleRec(**b) for b in p["bundles"]],
            )
        self.pending_pgs = deque(state["pending_pgs"])
        for nid, inc in (state.get("node_incarnations") or {}).items():
            self._node_incarnations[nid] = max(
                int(inc), self._node_incarnations.get(nid, 0)
            )
        for r in state["objects"]:
            rec = ObjectRec(
                oid=r["oid"], shm_name=r["shm_name"], size=r["size"],
                owner=r["owner"], node_id=r["node_id"], copies=r["copies"],
                owner_released=r["owner_released"], contains=r["contains"],
                cnt_pairs=r.get("cnt_pairs"),
                spill_path=r.get("spill_path"), pending_free=r.get("pending_free"),
            )
            rec.holders = set(r["holders"])
            self.objects[rec.oid] = rec
        self.leases = state["leases"]
        self._lease_shapes = state["lease_shapes"]
        self._lease_pg = {k: tuple(v) for k, v in state["lease_pg"].items()}
        self._lease_node = state["lease_node"]
        self.stats.update(state["stats"])
        for cid, entries in state.get("owner_digests") or ():
            self.owner_digests[cid] = {bytes(oid): info for oid, info in entries}
        ha = state.get("ha") or {}
        self.head_epoch = max(self.head_epoch, int(ha.get("epoch") or 1))
        self._ha_restored_addr = ha.get("tcp_addr") or None

    async def _persist_loop(self):
        """Debounced snapshot writer: at most one disk write per interval.
        Doubles as the lease-contention re-nudge tick: while requests are
        still queued, keep hinting holders to shed idle leases (the arrival-
        time nudge alone misses holders whose leases go idle later)."""
        while not self._shutdown.is_set():
            await asyncio.sleep(0.25)
            if self.ha_role == "demoted":
                # a fenced zombie must not clobber the successor's snapshot
                # or keep streaming stale deltas
                continue
            if self.pending_leases:
                self._last_reclaim_nudge = 0.0  # bypass the debounce
                self._nudge_lease_holders(requester="")
                self._expire_lease_requests()
            if self._needs_reclaim():
                # central work starved while capacity sits in agents' lease
                # blocks: revoke the unleased slots (reclaim arbiter role)
                self._last_central_demand = time.monotonic()
                self._reclaim_delegations()
            if self._repl_subs:
                self._repl_tick()
            if self._dirty:
                self._dirty = False
                try:
                    self._save_snapshot()
                except Exception as e:
                    self._log_event("snapshot_save_failed", error=repr(e))

    # head event kind -> flight-recorder plane (prefix match, first wins);
    # unmatched kinds file under "head"
    _FLIGHTREC_PLANES = (
        ("ha_", "ha"),
        ("rpc_fenced", "fence"),
        ("agent_register_fenced", "fence"),
        ("node_readopted", "fence"),
        ("net_chaos", "chaos"),
        ("drain", "drain"),
        ("node_drain", "drain"),
        ("object_lost", "ownership"),
        ("owners_adopted", "ownership"),
        ("owner", "ownership"),
        ("actor", "actor"),
        ("node", "node"),
        ("serve", "serve"),
        ("train", "train"),
        ("job", "job"),
    )

    def _log_event(self, kind: str, **fields):
        import json as _json

        ts = time.time()
        if self._flightrec_on:
            # mirror into the merged journal: head decisions and shipped
            # worker slices interleave in one queryable ring
            plane = "head"
            for prefix, p in self._FLIGHTREC_PLANES:
                if kind.startswith(prefix):
                    plane = p
                    break
            self.flightrec.append(
                {"ts": ts, "plane": plane, "event": kind, "node": LOCAL_NODE,
                 "proc": "head", **fields}
            )
        try:
            self._event_log.write(
                _json.dumps({"ts": ts, "event": kind, **fields}) + "\n"
            )
        except Exception:
            pass

    def _ingest_flightrec(self, evs) -> None:
        """Merge a shipped journal slice (metrics_report / node_sync
        piggyback) into the cluster ring.  Slices from different nodes
        interleave by arrival; queries sort by timestamp."""
        if not evs or not self._flightrec_on:
            return
        for ev in evs:
            if isinstance(ev, dict):
                self.flightrec.append(ev)

    # ------------------------------------------------------------- HA plane
    # Warm-standby replication + epoch-fenced promotion.  The active head
    # streams its registry mutations — the same tables _snapshot_state
    # serializes — to subscribed standbys over a versioned record stream
    # (the DeltaReporter idiom from core/ownership.py, head-scale): per-table
    # deltas ride the persist tick, KV commits replicate SYNCHRONOUSLY
    # before their reply (acked == survives head death), and a bounded
    # in-memory log re-stages records for standbys that reconnect with a
    # watermark.  Authority is the monotonic head epoch; see _handle's gate.

    _HA_PASSIVE_METHODS = frozenset({"ha_status", "head_promote"})

    def _ha_standby_addrs(self) -> List[str]:
        return sorted(
            {s["addr"] for s in self._repl_subs.values() if s.get("addr")}
        )

    def _ha_ring_broadcast(self) -> None:
        """Push the current standby ring + head epoch to every connected
        agent.  Register replies already carry both, but an agent that
        joined BEFORE a standby subscribed would otherwise never learn the
        successor's address — and a one-head ring means no failover."""
        standbys = self._ha_standby_addrs()
        for node in list(self.nodes.values()):
            if node.state == "dead" or node.conn is None:
                continue
            try:
                node.conn.notify(
                    "ha_ring", standbys=standbys, head_epoch=self.head_epoch,
                )
            except Exception:
                pass
        frame = {"m": "ha_ring", "standbys": standbys,
                 "head_epoch": self.head_epoch}
        for cid, state in list(self._clients.items()):
            if cid in self._repl_subs:
                continue  # the standby already knows the ring (it IS in it)
            try:
                write_frame(state["writer"], frame)
            except Exception:
                pass

    def _ha_status_dict(self) -> dict:
        lag = 0
        if self._repl_subs:
            lag = self._repl_seq - min(s["acked"] for s in self._repl_subs.values())
        return {
            "role": self.ha_role,
            "epoch": self.head_epoch,
            "rank": self.ha_rank,
            "seq": self._repl_seq,
            "watermark": self._ha_watermark,
            "addr": self.tcp_addr,
            "active_addr": self._ha_active_addr,
            "repl_lag": lag,
            "standbys": [
                {"addr": s.get("addr"), "rank": s.get("rank", 0),
                 "acked": s["acked"], "lag": self._repl_seq - s["acked"]}
                for s in self._repl_subs.values()
            ],
            "promotions": self.stats.get("ha_promotions", 0),
            "demotions": self.stats.get("ha_demotions", 0),
        }

    async def _h_ha_status(self, state, msg, reply, reply_err):
        reply(**self._ha_status_dict())

    def _ha_refuse(self, state, msg, reply_err, stale_client: bool = False) -> None:
        """Refuse an RPC this head has no authority to execute (standby or
        demoted role, or a client stamped with a superseded head epoch).

        Deliberately NOT a FencedError: that error (and the `fenced` push)
        tells a worker ITS node was declared dead, making it cancel leases
        and exit — wrong when the HEAD is the stale party.  A plain
        ConnectionError + closed socket sends the client back through its
        redial ring, where the register reply teaches it the real epoch."""
        self.stats["ha_refused_rpcs"] = self.stats.get("ha_refused_rpcs", 0) + 1
        if msg.get("i") is not None:
            if self.ha_role == "standby":
                reply_err(ConnectionError(
                    f"standby head (rank {self.ha_rank}) is not active; "
                    f"active head: {self._ha_active_addr or 'unknown'}"
                ))
            else:
                reply_err(ConnectionError(
                    f"head epoch {self.head_epoch} is no longer "
                    f"authoritative (successor epoch "
                    f"{self._ha_observed_epoch or '>' + str(self.head_epoch)})"
                    if self.ha_role == "demoted"
                    else f"request stamped with a superseded head epoch "
                         f"(current: {self.head_epoch}); re-register"
                ))
        if self.ha_role == "demoted" or stale_client:
            try:
                fence_close(state["writer"])
            except Exception:
                pass

    # -- active side: record stream --------------------------------------
    async def _h_head_replicate(self, state, msg, reply, reply_err):
        """A standby subscribes to the replication stream.  Records then
        flow as `repl` push frames on this connection — one ordered stream,
        so a table delta can never overtake a KV record it already
        contains.  Re-subscribes send their durable watermark: inside the
        re-stage window they get just the gap, otherwise a full transfer."""
        peer_epoch = int(msg.get("hepoch") or 0)
        if peer_epoch > self.head_epoch:
            # the subscriber outranks us — it was promoted while we were
            # away.  Demote; the FencedError marks this as an authority
            # verdict (the one case a head fences a head).
            self._ha_demote(peer_epoch, via="head_replicate")
            reply_err(FencedError(
                f"head epoch {self.head_epoch} superseded by promoted "
                f"standby at epoch {peer_epoch}"
            ))
            return
        cid = (msg.get("client_id") or state.get("client_id")
               or f"standby@{msg.get('addr') or id(state)}")
        state["client_id"] = cid
        self._clients[cid] = state
        sub = {
            "writer": state["writer"],
            "addr": msg.get("addr") or "",
            "rank": int(msg.get("rank") or 0),
            "acked": int(msg.get("watermark") or 0),
            "event": asyncio.Event(),
        }
        self._repl_subs[cid] = sub
        self._repl_table_digests.clear()  # next delta tick re-baselines
        self._log_event(
            "ha_standby_sub", addr=sub["addr"], rank=sub["rank"],
            watermark=sub["acked"], seq=self._repl_seq,
        )
        self._ha_ring_broadcast()
        reply(epoch=self.head_epoch, seq=self._repl_seq)
        watermark = sub["acked"]
        base = self._repl_log[0][0] if self._repl_log else self._repl_seq + 1
        if watermark and watermark + 1 >= base and watermark <= self._repl_seq:
            # bounded re-stage: replay only the records past the standby's
            # durable watermark (all still in the in-memory window)
            for seq, rec in list(self._repl_log):
                if seq > watermark:
                    self._repl_push(cid, sub, rec)
        else:
            # fresh standby, or a watermark older than the window: full
            # state transfer supersedes whatever it holds
            import msgpack

            blob = msgpack.packb(self._snapshot_state(), use_bin_type=True)
            sub["acked"] = 0
            self._repl_push(
                cid, sub,
                {"t": "full", "seq": self._repl_seq, "state": blob,
                 "epoch": self.head_epoch},
            )

    async def _h_head_replicate_ack(self, state, msg, reply, reply_err):
        sub = self._repl_subs.get(state.get("client_id") or "")
        if sub is not None:
            sub["acked"] = max(sub["acked"], int(msg.get("seq") or 0))
            sub["event"].set()

    def _repl_push(self, cid: str, sub: dict, rec: dict) -> None:
        try:
            # push stream consumed by _ha_on_repl_push on the standby:
            # ca-lint: ignore[rpc-unknown-method]
            write_frame(sub["writer"], {"m": "repl", **rec})
        except Exception:
            self._repl_drop_sub(cid, "write_failed")

    def _repl_send(self, rec: dict) -> None:
        """Append to the bounded re-stage log and push to every standby."""
        self._repl_log.append((rec["seq"], rec))
        self.stats["ha_records_streamed"] = (
            self.stats.get("ha_records_streamed", 0) + 1
        )
        for cid, sub in list(self._repl_subs.items()):
            self._repl_push(cid, sub, rec)

    def _repl_drop_sub(self, cid: str, reason: str) -> None:
        sub = self._repl_subs.pop(cid, None)
        if sub is None:
            return
        sub["event"].set()  # wake any sync commit waiting on this replica
        self.stats["ha_standbys_lost"] = (
            self.stats.get("ha_standbys_lost", 0) + 1
        )
        self._log_event("ha_standby_lost", addr=sub.get("addr"), reason=reason)
        self._ha_ring_broadcast()

    async def _repl_commit(self, rec: dict) -> None:
        """Synchronously replicate one record: return once every live
        standby acked it (applied in memory AND journaled) or got dropped
        at the timeout (availability over sync once a replica is gone).
        The caller's reply is the client-visible ack, so this is what makes
        'acked' mean 'survives head death'."""
        self._repl_seq += 1
        rec = {**rec, "seq": self._repl_seq, "epoch": self.head_epoch}
        self._repl_send(rec)
        self.stats["ha_sync_commits"] = self.stats.get("ha_sync_commits", 0) + 1
        loop = asyncio.get_running_loop()
        deadline = loop.time() + float(
            getattr(self.config, "ha_sync_commit_timeout_s", 2.0)
        )
        for cid in list(self._repl_subs):
            while True:
                sub = self._repl_subs.get(cid)
                if sub is None or sub["acked"] >= rec["seq"]:
                    break
                remaining = deadline - loop.time()
                if remaining <= 0:
                    self.stats["ha_sync_commit_timeouts"] = (
                        self.stats.get("ha_sync_commit_timeouts", 0) + 1
                    )
                    self._repl_drop_sub(cid, "sync_commit_timeout")
                    break
                sub["event"].clear()
                try:
                    # asyncio.Event.wait (coroutine), awaited via wait_for:
                    # ca-lint: ignore[async-blocking-call]
                    await asyncio.wait_for(sub["event"].wait(), remaining)
                except asyncio.TimeoutError:
                    pass

    def _repl_tick(self) -> None:
        """Table-delta replication (rides the persist loop): serialize the
        snapshot tables and stream only those whose bytes changed since the
        last tick.  A no-op tick degrades to a bare heartbeat so standbys
        can tell a quiet head from a dead one."""
        import zlib as _zlib

        import msgpack

        if self._repl_dirty:
            self._repl_dirty = False
            changed = {}
            for name, val in self._snapshot_state().items():
                blob = msgpack.packb(val, use_bin_type=True)
                digest = _zlib.crc32(blob)
                if self._repl_table_digests.get(name) != digest:
                    self._repl_table_digests[name] = digest
                    changed[name] = blob
            if changed:
                self._repl_seq += 1
                self._repl_send(
                    {"t": "tables", "seq": self._repl_seq,
                     "tables": changed, "epoch": self.head_epoch}
                )
                return
        self._repl_send(
            {"t": "hb", "seq": self._repl_seq, "epoch": self.head_epoch}
        )

    # -- standby side: subscribe/apply loop ------------------------------
    async def _ha_standby_loop(self):
        """Standby FSM: recover the local journal, subscribe to the active
        head with the durable watermark, apply pushed records, and promote
        when the active head stays unreachable past the grace window
        (rank-staggered so replicas never race for the epoch)."""
        from ..util import replog
        from ..util.aio import dial

        path = os.path.join(
            self.session_dir, f"head.standby{self.ha_rank}.replog"
        )
        records, torn = replog.recover(path)
        if torn:
            self._log_event("ha_repl_torn_tail", path=path, intact=len(records))
        self._ha_shadow, self._ha_watermark = replog.replay(records)
        self._ha_replog = replog.ReplLogWriter(path)
        addrs = [
            a for a in (os.environ.get("CA_HEAD_ADDR") or "").split(",") if a
        ]
        grace = float(getattr(self.config, "ha_failover_grace_s", 2.0))
        grace *= 1.0 + self.ha_rank  # rank stagger
        auto = bool(getattr(self.config, "ha_auto_promote", True))
        from .worker import _redial_backoff

        down_since: Optional[float] = None
        attempt = 0
        while not self._shutdown.is_set() and self.ha_role == "standby":
            loop = asyncio.get_running_loop()
            now = loop.time()
            conn = self._ha_active_conn
            if conn is not None and not conn.closed:
                if now - self._ha_last_rx > max(grace, 2.0):
                    # socket open but the stream went silent (partitioned
                    # or wedged active): treat as down and redial
                    await conn.close()
                else:
                    down_since = None
                    attempt = 0
                    await asyncio.sleep(0.1)
                    continue
            self._ha_active_conn = None
            if down_since is None:
                down_since = now
            for addr in addrs:
                try:
                    conn = await dial(
                        addr, purpose="head (standby sync)",
                        timeout=min(2.0, self.config.dial_timeout_s),
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    continue
                conn.set_push_handler(self._ha_on_repl_push)
                # assigned before the subscribe call: replayed records can
                # arrive on this conn before call() returns, and the push
                # handler acks through _ha_active_conn
                self._ha_active_conn = conn
                self._ha_last_rx = loop.time()
                try:
                    r = await conn.call(
                        "head_replicate",
                        client_id=f"standby-{self.ha_rank}-{os.getpid()}",
                        addr=self.tcp_addr, rank=self.ha_rank,
                        watermark=self._ha_watermark,
                        hepoch=self.head_epoch, timeout=5,
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    self._ha_active_conn = None
                    try:
                        await conn.close()
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        pass
                    continue
                self.head_epoch = max(self.head_epoch, int(r.get("epoch") or 1))
                self._ha_active_addr = addr
                down_since = None
                attempt = 0
                self._log_event(
                    "ha_standby_synced", active=addr, epoch=self.head_epoch,
                    watermark=self._ha_watermark,
                )
                break
            if self._ha_active_conn is not None:
                continue
            now = loop.time()
            if auto and down_since is not None and now - down_since > grace:
                await self._ha_promote(reason="active head unreachable")
                return
            attempt += 1
            await asyncio.sleep(min(_redial_backoff(attempt), 0.5))

    async def _ha_on_repl_push(self, msg):
        if msg.get("m") != "repl":
            return
        loop = asyncio.get_running_loop()
        self._ha_last_rx = loop.time()
        ep = int(msg.get("epoch") or 0)
        if ep > self.head_epoch:
            self.head_epoch = ep
        t = msg.get("t")
        if t == "hb":
            return
        seq = int(msg.get("seq") or 0)
        if t != "full" and seq <= self._ha_watermark:
            return  # re-stage overlap: already applied and journaled
        from ..util import replog

        rec = {k: v for k, v in msg.items() if k != "m"}
        try:
            self._ha_shadow = replog.apply_record(self._ha_shadow, rec)
        except Exception as e:
            # never ack a record we could not apply: drop the stream and
            # resubscribe from the durable watermark instead
            self._log_event("ha_apply_failed", seq=seq, error=repr(e))
            conn = self._ha_active_conn
            if conn is not None:
                await conn.close()
            return
        if self._ha_replog is not None:
            try:
                if t == "full":
                    self._ha_replog.reset()  # full state supersedes history
                self._ha_replog.append(rec)
            except OSError:
                pass
        self._ha_watermark = seq
        conn = self._ha_active_conn
        if conn is not None and not conn.closed:
            try:
                conn.notify("head_replicate_ack", seq=seq)
            except Exception:
                pass

    # -- role transitions --------------------------------------------------
    async def _ha_promote(self, reason: str) -> dict:
        """Standby -> active: adopt the replicated state, mint the successor
        epoch, claim the session discovery files (head.addr / head.sock /
        head.ready), and start the active-only loops."""
        if self.ha_role == "active":
            return self._ha_status_dict()
        if self.ha_role == "demoted":
            raise RuntimeError("demoted head cannot promote")
        if self._ha_shadow is not None:
            self._load_state(self._ha_shadow)  # maxes head_epoch with ha.epoch
        self.head_epoch += 1  # the successor epoch: strictly above anything seen
        self.ha_role = "active"
        self._restored = True  # suppress prestart; re-adopt live survivors
        self.stats["ha_promotions"] = self.stats.get("ha_promotions", 0) + 1
        conn, self._ha_active_conn = self._ha_active_conn, None
        if conn is not None and not conn.closed:
            try:
                await conn.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
        # re-anchor liveness: the restored tables carry the OLD head's view;
        # survivors get the same reconnect grace a snapshot restart gives
        now = time.monotonic()
        for node in self.nodes.values():
            node.last_heartbeat = now
        for w in self.workers.values():
            w.last_heartbeat = now
        # claim the discovery files: session-dir drivers and head.addr
        # readers now find THIS head
        sock = os.path.join(self.session_dir, "head.sock")
        try:
            os.unlink(sock)
        except OSError:
            pass
        try:
            self._sock_server = Server([sock], self._handle, self._on_disconnect)
            await self._sock_server.start()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._log_event("ha_promote_sock_failed", error=repr(e))
            self._sock_server = None
        addr_file = os.path.join(self.session_dir, "head.addr")
        with open(addr_file + ".tmp", "w") as f:
            f.write(self.tcp_addr or "")
        os.replace(addr_file + ".tmp", addr_file)
        ready = os.path.join(self.session_dir, "head.ready")
        with open(ready + ".tmp", "w") as f:
            f.write(str(os.getpid()))
        os.replace(ready + ".tmp", ready)
        self._ckpt_path = os.path.join(self.session_dir, "head.ckpt")
        self._dirty = True
        try:
            self._save_snapshot()
        except Exception as e:
            self._log_event("snapshot_save_failed", error=repr(e))
        self._ha_start_active_loops()
        self._log_event(
            "ha_promote", epoch=self.head_epoch, reason=reason,
            watermark=self._ha_watermark, nodes=len(self.nodes),
            workers=len(self.workers),
        )
        return self._ha_status_dict()

    async def _h_head_promote(self, state, msg, reply, reply_err):
        try:
            reply(**(await self._ha_promote(reason=msg.get("reason") or "rpc")))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            reply_err(e)

    def _ha_demote(self, observed: Optional[int], via: str) -> None:
        """Active -> demoted: a successor epoch exists, so every table here
        is a zombie's view.  Stop persisting/streaming, drop all clients so
        nothing keeps talking to this registry, and exit shortly — the
        successor owns the workers and the shm namespace now."""
        if self.ha_role == "demoted":
            return
        was = self.ha_role
        self.ha_role = "demoted"
        if observed:
            self._ha_observed_epoch = max(self._ha_observed_epoch, observed)
        self.stats["ha_demotions"] = self.stats.get("ha_demotions", 0) + 1
        self._log_event(
            "ha_demote", epoch=self.head_epoch,
            observed=observed or self._ha_observed_epoch, via=via, was=was,
        )
        for st in list(self._clients.values()):
            try:
                fence_close(st["writer"])
            except Exception:
                pass
        spawn_bg(self._ha_demote_exit())

    async def _ha_demote_exit(self):
        # small grace so refusal replies flush before the process exits
        await asyncio.sleep(0.5)
        self._shutdown.set()

    async def _ha_boot_probe(self) -> bool:
        """A restarting head checks whether head.addr now names a DIFFERENT
        live head before claiming authority: if that head answers with an
        epoch >= ours, THIS process is the stale one — demote at boot
        instead of split-braining the registry.  True = demoted."""
        if not bool(getattr(self.config, "ha_boot_probe", True)):
            return False
        try:
            other = open(
                os.path.join(self.session_dir, "head.addr")
            ).read().strip()
        except OSError:
            return False
        if not other or other == self.tcp_addr:
            return False
        from ..util.aio import dial

        from ..util.aio import finally_await

        try:
            conn = await dial(other, purpose="head (boot probe)", timeout=2.0)
        except asyncio.CancelledError:
            raise
        except Exception:
            return False  # unreachable: nothing live to defer to
        try:
            st = await conn.call("ha_status", timeout=2.0)
        except asyncio.CancelledError:
            raise
        except Exception:
            return False
        finally:
            await finally_await(conn.close(), "boot-probe close")
        ep = int(st.get("epoch") or 0)
        if st.get("role") == "active" and ep >= self.head_epoch:
            self._ha_demote(ep, via="boot_probe")
            return True
        return False

    def _ha_start_active_loops(self) -> None:
        from ..util.aio import spawn_logged

        if self._ha_loops_started:
            return
        self._ha_loops_started = True
        self._ha_tasks = [
            spawn_logged(self._monitor_loop(), "head-monitor"),
            spawn_logged(self._persist_loop(), "head-persist"),
            spawn_logged(self._log_tail_loop(), "head-log-tail"),
            spawn_logged(self._loop_lag_loop(), "head-loop-lag"),
        ]

    # ---------------------------------------------------------------- utils
    def _pub(self, channel: str, data: dict):
        dead = []
        for w in self.subscribers.get(channel, []):
            try:
                write_frame(w, {"m": "pub", "ch": channel, "data": data})
            except Exception:
                dead.append(w)
        for w in dead:
            self.subscribers[channel].remove(w)

    def _fits(self, avail: Dict[str, float], shape: Dict[str, float]) -> bool:
        return scheduling.fits(avail, shape)

    def _take(self, avail: Dict[str, float], shape: Dict[str, float]):
        for k, v in shape.items():
            avail[k] = avail.get(k, 0.0) - v

    def _give(self, avail: Dict[str, float], shape: Dict[str, float]):
        for k, v in shape.items():
            avail[k] = avail.get(k, 0.0) + v

    # ------------------------------------------------------------ worker pool
    def _new_wid(self) -> str:
        self._spawn_count += 1
        return f"w{self._spawn_count:04d}"

    def _spawn_worker(self, purpose: str = "pool", pool: str = "cpu") -> WorkerRec:
        """Spawn a worker process on the local (head-embedded) node."""
        wid = self._new_wid()
        addr = os.path.join(self.session_dir, f"{wid}.sock")
        log_path = os.path.join(self.session_dir, f"{wid}.log")
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = self.session_dir
        env["CA_HEAD_SOCK"] = self.sock_path
        env["CA_WORKER_ID"] = wid
        env["CA_WORKER_SOCK"] = addr
        env["CA_NODE_ID"] = LOCAL_NODE
        env["CA_CONFIG_JSON"] = self.config.to_json()
        if pool != "tpu":
            # CPU workers must not grab the accelerator: drop the TPU runtime
            # hook (which also costs ~2s of jax import at interpreter start)
            # and pin jax to the host platform if user code imports it.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        chip = None
        if pool == "tpu" and self._chip_alloc is not None:
            # pin each TPU worker to one chip (set_current_process_visible_
            # accelerator_ids analogue) so concurrent workers don't fight
            # over the device; single-chip hosts leave the env untouched
            from . import accelerators

            chip = self._chip_alloc.acquire()
            env.update(accelerators.visible_chips_env_for_worker(chip))
        logf = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.workerproc"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        rec = WorkerRec(
            worker_id=wid, pid=proc.pid, addr=addr, proc=proc, purpose=purpose, pool=pool,
            tpu_chip=chip,
        )
        self.workers[wid] = rec
        self.stats["workers_spawned"] += 1
        return rec

    def _spawn_worker_on(self, node: NodeRec, purpose: str = "pool", pool: str = "cpu") -> WorkerRec:
        """Spawn a worker on any node: directly for the local node, via the
        node agent RPC otherwise (the agent is the raylet-analogue process
        that owns worker lifecycles on its host)."""
        if node.is_local:
            return self._spawn_worker(purpose=purpose, pool=pool)
        wid = self._new_wid()
        rec = WorkerRec(worker_id=wid, pid=0, addr="", node_id=node.node_id,
                        purpose=purpose, pool=pool)
        self.workers[wid] = rec
        self.stats["workers_spawned"] += 1

        async def _ask_agent():
            try:
                await node.conn.call("spawn_worker", wid=wid, purpose=purpose, pool=pool)
            except asyncio.CancelledError:
                raise  # head shutdown: not a spawn failure
            except Exception:
                rec.state = "dead"
                fut = self._register_waiters.pop(wid, None)
                if fut is not None and not fut.done():
                    fut.set_result(False)
                # a pending lease may have been waiting on this spawn; give
                # the scheduler a chance to spawn elsewhere
                self._service_queue()

        spawn_bg(_ask_agent())
        return rec

    async def _worker_conn(self, rec: WorkerRec) -> Connection:
        conn = self._worker_conns.get(rec.worker_id)
        if conn is None or conn.closed:
            from ..util.aio import dial  # lazy: util/__init__ reaches into core

            conn = await dial(
                rec.addr, purpose=f"worker {rec.worker_id}",
                peer_node=rec.node_id,
            )
            self._worker_conns[rec.worker_id] = conn
        return conn

    async def _wait_registered(self, rec: WorkerRec) -> bool:
        if rec.state != "starting":
            return rec.state != "dead"
        fut = self._register_waiters.setdefault(
            rec.worker_id, asyncio.get_running_loop().create_future()
        )
        try:
            await asyncio.wait_for(fut, self.config.worker_register_timeout_s)
            return True
        except asyncio.TimeoutError:
            return False

    @staticmethod
    def _pool_key(shape: Dict[str, float]) -> str:
        return "tpu" if shape.get("TPU") else "cpu"

    def _ensure_pool(self):
        """Prestart/grow per-node worker pools when demand outstrips idle
        workers.  Demand is computed by simulating placement of the queued
        lease requests onto the alive nodes (policy-faithful: spawn where the
        scheduler will grant), capped by each node's free resources."""
        alive = self._alive_nodes()
        if not alive:
            return
        views = self._node_views(alive)
        demand: Dict[tuple, int] = {}
        for r in self.pending_leases:
            pool = self._pool_key(r.shape)
            if r.pg_id:
                pg = self.pgs.get(r.pg_id)
                if pg is None or pg.state != "created":
                    continue
                if not (0 <= r.bundle_index < len(pg.bundles)):
                    continue
                nid = pg.bundles[r.bundle_index].node_id
                if nid is None:
                    continue
                demand[(nid, pool)] = demand.get((nid, pool), 0) + 1
            else:
                view = scheduling.pick_node(
                    views, r.shape, r.strategy, self.config.scheduler_spread_threshold
                )
                if view is None:
                    continue
                scheduling.take(view.avail, r.shape)
                demand[(view.node_id, pool)] = demand.get((view.node_id, pool), 0) + 1
        per_node_alive: Dict[str, int] = {}
        per_node_starting: Dict[tuple, int] = {}
        for w in self.workers.values():
            if w.state != "dead":
                per_node_alive[w.node_id] = per_node_alive.get(w.node_id, 0) + 1
            if w.state == "starting" and w.purpose == "pool":
                key = (w.node_id, w.pool)
                per_node_starting[key] = per_node_starting.get(key, 0) + 1
        for (nid, pool), d in demand.items():
            node = self.nodes.get(nid)
            if node is None or node.state != "alive":
                continue
            want = d - len(node.idle[pool]) - per_node_starting.get((nid, pool), 0)
            n_alive = per_node_alive.get(nid, 0)
            while want > 0 and n_alive < node.max_workers:
                self._spawn_worker_on(node, pool=pool)
                want -= 1
                n_alive += 1
                per_node_alive[nid] = n_alive

    # ------------------------------------------------------------- scheduler
    def _bundle_avail(self, pg_id: str, bundle_index: int) -> Optional[Dict[str, float]]:
        pg = self.pgs.get(pg_id)
        if pg is None or not (0 <= bundle_index < len(pg.bundles)):
            return None
        b = pg.bundles[bundle_index]
        return {k: v - b.used.get(k, 0.0) for k, v in b.resources.items()}

    def _grant_on_node(self, node: NodeRec, req: LeaseReq) -> bool:
        """Pop an idle worker of the right pool on `node` and grant the lease.
        Returns False if the node has no usable idle worker."""
        pool = node.idle[self._pool_key(req.shape)]
        while pool:
            wid = pool.popleft()
            rec = self.workers.get(wid)
            if rec is None or rec.state != "idle":
                continue
            if req.pg_id:
                b = self.pgs[req.pg_id].bundles[req.bundle_index]
                for k, v in req.shape.items():
                    b.used[k] = b.used.get(k, 0.0) + v
            else:
                self._take(node.avail, req.shape)
            lease_id = f"l{os.urandom(6).hex()}"
            rec.state = "leased"
            rec.busy_since = time.monotonic()
            rec.lease_id = lease_id
            self.leases[lease_id] = wid
            self._lease_shapes[lease_id] = dict(req.shape)
            self._lease_node[lease_id] = node.node_id
            self._lease_client[lease_id] = req.client
            if req.pg_id:
                self._lease_pg[lease_id] = (req.pg_id, req.bundle_index)
            self.stats["leases_granted"] += 1
            # node travels with the grant so the submitter can tell a drain
            # kill (system failure, free retry) from an app crash
            req.reply(
                lease_id=lease_id,
                worker_id=wid,
                addr=self._addr_for(rec, req.remote),
                node=node.node_id,
            )
            return True
        return False

    def _try_grant(self, req: LeaseReq) -> bool:
        # resource admission: from a PG bundle (on the bundle's node) or from
        # a node chosen by the scheduling policy
        if req.pg_id:
            pg = self.pgs.get(req.pg_id)
            if pg is not None and pg.state != "created":
                # bundles of a pending PG were never deducted from any node's
                # avail; granting against them would oversubscribe — wait
                # (requeue) until _service_pending_pgs places the PG
                return False
            avail = self._bundle_avail(req.pg_id, req.bundle_index)
            if avail is None:
                req.reply_err(PlacementGroupError(f"placement group {req.pg_id} not found"))
                return True
            if not self._fits(avail, req.shape):
                return False
            nid = pg.bundles[req.bundle_index].node_id
            node = self.nodes.get(nid)
            if node is None or node.state != "alive":
                return False
            return self._grant_on_node(node, req)
        # policy-ranked candidates; grant on the first that has an idle
        # worker.  Ranking reads NodeRecs in place (no snapshot copies): this
        # runs per queued request per scheduling pass, and the single-node
        # case must stay allocation-free for task-throughput.
        alive = self._alive_nodes()
        threshold = self.config.scheduler_spread_threshold
        kind = (req.strategy or {}).get("type", "DEFAULT")
        if kind == "NODE_AFFINITY":
            want = req.strategy.get("node_id")
            node = self.nodes.get(want)
            if node is not None and node.state == "alive" and self._fits(node.avail, req.shape):
                if self._grant_on_node(node, req):
                    return True
                return False  # wait for a worker on that node
            if not req.strategy.get("soft", False):
                if node is None or node.state != "alive":
                    req.reply_err(
                        ValueError(f"node {want!r} not available for NODE_AFFINITY")
                    )
                    return True
                return False
            kind = "DEFAULT"
        if kind == "NODE_LABEL":
            # label-filtered candidates (hard drops, soft prefers); an
            # unmatchable selector leaves the request pending, same as an
            # unsatisfiable resource shape — a matching node may join later
            alive = scheduling.filter_rank_labels(alive, req.strategy, threshold)
        elif len(alive) > 1:
            # rank over the live NodeRecs in place (no snapshot copies)
            if kind == "SPREAD":
                alive = scheduling.rank_spread(alive)
            else:
                alive = scheduling.rank_hybrid(alive, threshold)
        if kind == "SPREAD":
            # spread semantics: hold the request for the policy-chosen node
            # even when its worker pool is still spawning — skipping to
            # whichever node already has an idle worker would pack the flood
            # onto the few warm nodes (the opposite of SPREAD)
            for node in alive:
                if not scheduling.fits(node.avail, req.shape):
                    continue
                return self._grant_on_node(node, req)
            return False
        for node in alive:
            if not scheduling.fits(node.avail, req.shape):
                continue
            if self._grant_on_node(node, req):
                return True
        return False

    def _service_queue(self):
        # pending PGs reserve first: their creation was requested before the
        # queued leases could possibly run inside them
        self._service_pending_pgs()
        made_progress = True
        while made_progress and self.pending_leases:
            made_progress = False
            for _ in range(len(self.pending_leases)):
                req = self.pending_leases.popleft()
                if self._try_grant(req):
                    made_progress = True
                else:
                    self.pending_leases.append(req)
        self._ensure_pool()
        # whatever idle capacity central work didn't claim flows out to the
        # agents' lease blocks (node-local granting)
        self._maybe_delegate()

    def _release_lease(self, lease_id: str, worker_ok: bool = True):
        wid = self.leases.pop(lease_id, None)
        shape = self._lease_shapes.pop(lease_id, None)
        pg = self._lease_pg.pop(lease_id, None)
        nid = self._lease_node.pop(lease_id, None)
        self._lease_client.pop(lease_id, None)
        if shape is not None:
            if pg is not None:
                pgrec = self.pgs.get(pg[0])
                if pgrec is not None:
                    b = pgrec.bundles[pg[1]]
                    for k, v in shape.items():
                        b.used[k] = b.used.get(k, 0.0) - v
            else:
                node = self.nodes.get(nid or LOCAL_NODE)
                if node is not None and node.up:
                    self._give(node.avail, shape)
        if wid is not None:
            rec = self.workers.get(wid)
            if rec is not None and rec.state == "leased":
                if worker_ok:
                    rec.state = "idle"
                    rec.lease_id = None
                    node = self.nodes.get(rec.node_id)
                    if node is not None and node.state == "alive":
                        node.idle[rec.pool].append(wid)
        self._service_queue()

    # ---------------------------------------------------------- lease plane
    def _lease_block_cap(self, node: NodeRec) -> int:
        cap = self.config.lease_block_max
        return cap if cap > 0 else int(node.total.get("CPU", 0))

    def _maybe_delegate(self):
        """Delegate idle agent-node workers into lease blocks (the head ->
        raylet capacity split).  Runs only when no central work is queued:
        pending leases/PGs get first claim on fresh idle workers, which also
        keeps delegation and revocation from ping-ponging."""
        if not self.config.lease_delegation:
            return
        if self._needs_reclaim():
            # the queued work needs CENTRAL capacity; ttl-marked escalation
            # probes don't block delegation — their submitters poll the
            # agents, so the capacity serves them faster delegated
            self._last_central_demand = time.monotonic()
            return
        if time.monotonic() - self._last_central_demand < 0.5:
            # central demand was queued moments ago (wave-shaped floods):
            # freshly idle workers serve the next wave centrally instead of
            # vanishing into blocks the next wave can't see
            return
        for node in self.nodes.values():
            if (
                node.is_local
                or node.state != "alive"
                or node.conn is None
                or node.conn.closed
            ):
                continue
            cap = self._lease_block_cap(node)
            for pool, unit in LEASE_UNIT_SHAPES.items():
                idle = node.idle.get(pool)
                if not idle:
                    continue
                delegated = node.delegated.setdefault(pool, set())
                grant: List[dict] = []
                while (
                    idle
                    and len(delegated) < cap
                    and scheduling.fits(node.avail, unit)
                ):
                    wid = idle.popleft()
                    rec = self.workers.get(wid)
                    if rec is None or rec.state != "idle":
                        continue
                    # charge the slot's unit shape NOW: central scheduling
                    # can never over-commit capacity an agent may grant
                    self._take(node.avail, unit)
                    rec.state = "delegated"
                    delegated.add(wid)
                    grant.append({"wid": wid, "addr": rec.addr})
                if grant:
                    try:
                        # the block carries the node's incarnation: an agent
                        # whose token disagrees discards the delegation (it
                        # is mid-fence and must not grant from stale blocks)
                        node.conn.notify(
                            "lease_block", pool=pool, workers=grant,
                            ninc=node.incarnation,
                        )
                        self.stats["lease_blocks_delegated"] += len(grant)
                        self._dirty = True
                    except Exception:
                        # push failed: undo — the agent never saw the block
                        for g in grant:
                            self._undelegate_wid(node, pool, g["wid"])

    def _undelegate_wid(self, node: NodeRec, pool: str, wid: str, dead: bool = False):
        """Take one worker slot back from a node's block accounting: credit
        the unit charge and (for live workers) rejoin the idle pool."""
        if wid not in node.delegated.get(pool, ()):
            return
        node.delegated[pool].discard(wid)
        if node.up:
            self._give(node.avail, LEASE_UNIT_SHAPES[pool])
        rec = self.workers.get(wid)
        if not dead and rec is not None and rec.state == "delegated":
            rec.state = "idle"
            if node.state == "alive" and wid not in node.idle[rec.pool]:
                node.idle[rec.pool].append(wid)

    def _expire_lease_requests(self):
        """Answer lease-plane escalation probes past their ttl with
        {"expired": True}: the submitter re-probes the agents' blocks and
        re-subscribes here.  Without expiry, one saturated-burst overflow
        request would sit pending forever and force block revocation —
        re-centralizing the exact traffic the lease plane exists to move."""
        now = time.monotonic()
        if not any(
            r.deadline is not None and r.deadline < now for r in self.pending_leases
        ):
            return
        keep: deque = deque()
        for r in self.pending_leases:
            if r.deadline is not None and r.deadline < now:
                r.reply(expired=True)
            else:
                keep.append(r)
        self.pending_leases = keep

    def _needs_reclaim(self) -> bool:
        """Should delegated capacity be pulled back?  Only for work the head
        ALONE can serve: pending PGs and classic (no-ttl) lease requests —
        PG-charged, strategy-constrained, custom-shaped, or remote-client
        leases.  ttl-marked requests are lease-plane escalation probes: their
        submitters are already polling the agents, so revoking for them would
        just re-centralize the hot class under load."""
        if self.pending_pgs:
            return True
        return any(r.deadline is None for r in self.pending_leases)

    def _reclaim_delegations(self):
        """Central work is queued while capacity sits delegated: ask agents
        to return their UNLEASED slots (the head is the reclaim arbiter).
        Debounced; runs from the 0.25s persist tick so transient queue blips
        during normal churn never thrash the blocks."""
        now = time.monotonic()
        if now - self._last_deleg_reclaim < 0.25:
            return
        self._last_deleg_reclaim = now
        for node in self.nodes.values():
            if node.state != "alive" or node.conn is None or node.conn.closed:
                continue
            for pool, wids in node.delegated.items():
                if wids:
                    try:
                        node.conn.notify("lease_block_revoke", pool=pool, n=len(wids))
                    except Exception:
                        pass

    async def _h_lease_block_return(self, state, msg, reply, reply_err):
        """Agent returned unleased block slots (revocation reply or agent-
        initiated shed): credit the charges, rejoin the idle pools, and let
        the queued central work grab the capacity."""
        node = self.nodes.get(msg.get("node_id", state.get("node_id")))
        if node is None:
            return
        pool = msg.get("pool", "cpu")
        n = 0
        for wid in msg.get("wids") or ():
            if wid in node.delegated.get(pool, ()):
                self._undelegate_wid(node, pool, wid)
                n += 1
        if n:
            self.stats["lease_blocks_returned"] += n
            self._service_queue()

    def _placeable_with_delegated(self, a: ActorRec) -> bool:
        """Would the actor place if every delegated-but-unleased slot came
        back?  Credits each block's full unit capacity to a hypothetical
        view — optimistic (leased slots won't return), so it gates a bounded
        reclaim-and-wait, never an unconditional one."""
        views = []
        for n in self._alive_nodes():
            avail = dict(n.avail)
            for pool, wids in n.delegated.items():
                for k, v in LEASE_UNIT_SHAPES[pool].items():
                    avail[k] = avail.get(k, 0.0) + v * len(wids)
            views.append(
                scheduling.NodeView(n.node_id, n.total, avail, n.index, labels=n.labels)
            )
        return (
            scheduling.pick_node(
                views, a.resources, a.strategy, self.config.scheduler_spread_threshold
            )
            is not None
        )

    def _fits_eventually(self, a: ActorRec) -> bool:
        """Could the actor place once currently-leased capacity returns?
        True when its shape fits some schedulable node's TOTAL resources —
        gates the bounded reclaim-and-wait above for busy-but-placeable
        actors; infeasible shapes keep their immediate failure."""
        views = [
            scheduling.NodeView(
                n.node_id, n.total, dict(n.total), n.index, labels=n.labels
            )
            for n in self._alive_nodes()
        ]
        return (
            scheduling.pick_node(
                views, a.resources, a.strategy,
                self.config.scheduler_spread_threshold,
            )
            is not None
        )

    def _reconcile_lease_blocks(self, node: NodeRec, blocks: Dict[str, dict]):
        """Adopt the agent's authoritative view of its delegated blocks (sent
        with every agent (re)registration).  After a head kill -9 + restart
        the snapshot may trail reality — grants and delegations made while
        the head was down — so the block membership reconciles both ways:
        workers the agent holds become `delegated` here (charged), workers
        the head thought delegated but the agent no longer holds go back to
        the idle pool (credited)."""
        for key in [k for k in self._pending_block_adopt if k[0] == node.node_id]:
            del self._pending_block_adopt[key]  # superseded by this snapshot
        for pool, unit in LEASE_UNIT_SHAPES.items():
            agent_wids = set((blocks.get(pool) or {}).get("wids") or ())
            head_wids = set(node.delegated.get(pool, ()))
            for wid in agent_wids - head_wids:
                rec = self.workers.get(wid)
                if rec is None:
                    # snapshotless restart, agent registered before this
                    # worker: adopt it into the block when IT re-registers
                    # (joining the idle pool instead would make one worker
                    # grantable by both planes)
                    self._pending_block_adopt[(node.node_id, wid)] = pool
                    continue
                if rec.state == "leased" and rec.lease_id:
                    # snapshot-stale central lease (returned pre-crash, after
                    # the last snapshot): the agent's newer block membership
                    # wins — retire the lease record first, then adopt, or a
                    # later release would rejoin the worker to the idle pool
                    # while the agent still grants it (dual-plane worker)
                    self._release_lease(rec.lease_id, worker_ok=True)
                if rec.state not in ("idle", "delegated", "starting"):
                    continue  # dead here: worker_exit settles it agent-side
                try:
                    node.idle[pool].remove(wid)
                except ValueError:
                    pass
                if rec.state != "delegated":
                    self._take(node.avail, unit)
                rec.state = "delegated"
                node.delegated.setdefault(pool, set()).add(wid)
            for wid in head_wids - agent_wids:
                self._undelegate_wid(node, pool, wid)
        self._dirty = True

    # --------------------------------------------------------------- actors
    async def _place_actor(self, a: ActorRec):
        """Pick a node for the actor, spawn a dedicated worker there, and run
        the actor creation task on it.  Mirrors GcsActorScheduler: lease
        resources, push creation, publish."""
        node: Optional[NodeRec] = None
        if a.pg_id:
            pg = self.pgs.get(a.pg_id)
            if pg is not None and pg.state == "pending":
                # wait for the PG's resources to actually be reserved; placing
                # into a pending PG would charge a bundle whose capacity was
                # never taken from a node (oversubscription)
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                self._pg_waiters.setdefault(a.pg_id, []).append(fut)
                try:
                    await fut
                except PlacementGroupError:
                    pass  # removed while pending: falls through to dead below
            avail = self._bundle_avail(a.pg_id, a.bundle_index)
            ok = avail is not None and self._fits(avail, a.resources)
            if ok:
                b = self.pgs[a.pg_id].bundles[a.bundle_index]
                node = self.nodes.get(b.node_id) if b.node_id else None
                ok = node is not None and node.state == "alive"
                if ok:
                    for k, v in a.resources.items():
                        b.used[k] = b.used.get(k, 0.0) + v
                    a.charged = "pg"
        else:
            view = scheduling.pick_node(
                self._node_views(), a.resources, a.strategy,
                self.config.scheduler_spread_threshold,
            )
            if view is None and (
                self._placeable_with_delegated(a) or self._fits_eventually(a)
            ):
                # the capacity exists but is parked in agents' lease blocks
                # or held by running task leases: reclaim (the head is the
                # arbiter) / wait for leases to idle-return instead of
                # failing a valid actor.  Restart/migration placements hit
                # this constantly — a drain evacuating an actor onto a
                # survivor whose CPUs are briefly all leased must wait out
                # the tasks, not die "resources unavailable".  Genuinely
                # infeasible shapes (fit no node's TOTAL) still fail fast.
                deadline = time.monotonic() + 10.0
                while view is None and time.monotonic() < deadline:
                    # re-stamped EVERY round: a lease_block_return landing
                    # after the quiet period would otherwise be re-delegated
                    # by its own _service_queue before this coroutine wakes
                    self._last_central_demand = time.monotonic()
                    self._last_deleg_reclaim = 0.0  # bypass the debounce
                    self._reclaim_delegations()
                    await asyncio.sleep(0.25)
                    view = scheduling.pick_node(
                        self._node_views(), a.resources, a.strategy,
                        self.config.scheduler_spread_threshold,
                    )
            ok = view is not None
            if ok:
                node = self.nodes[view.node_id]
                self._take(node.avail, a.resources)
                a.charged = "node"
        if not ok or node is None:
            a.state = "dead"
            a.death_cause = "resources unavailable for actor"
            self._pub("actors", self._actor_info(a))
            return
        a.node_id = node.node_id
        # incarnation guard: if this placement's worker dies mid-start (node
        # death, partition verdict), _on_worker_death fires a NEW restart at
        # a bumped incarnation — this superseded coroutine must then return
        # silently instead of stomping the actor dead over the fresh attempt
        placing_inc = a.incarnation
        rec = self._spawn_worker_on(node, purpose="actor", pool=self._pool_key(a.resources))
        rec.actor_id = a.actor_id
        a.worker_id = rec.worker_id
        if not await self._wait_registered(rec):
            if a.incarnation == placing_inc:
                a.state = "dead"
                a.death_cause = "actor worker failed to start"
                self._pub("actors", self._actor_info(a))
            return
        a.addr = rec.addr
        try:
            conn = await self._worker_conn(rec)
            await conn.call(
                "spawn_actor",
                actor_id=a.actor_id,
                fn_id=a.fn_id,
                init_spec=a.init_spec,
                max_concurrency=a.max_concurrency,
                concurrency_groups=a.concurrency_groups,
                incarnation=a.incarnation,
                runtime_env=a.runtime_env,
            )
            if a.incarnation != placing_inc:
                # superseded while spawning: the newer incarnation owns the
                # record now; this worker will be reaped as an orphan
                return
            a.state = "alive"
            self.stats["actors_created"] += 1
            self._log_event(
                "actor_alive", actor_id=a.actor_id, worker_id=a.worker_id, node_id=a.node_id
            )
        except asyncio.CancelledError:
            raise  # head shutdown mid-create: not an actor death
        except Exception as e:
            if a.incarnation != placing_inc:
                return
            a.state = "dead"
            a.death_cause = f"actor __init__ failed: {e!r}"
        self._pub("actors", self._actor_info(a))

    def _actor_info(self, a: ActorRec) -> dict:
        return {
            "actor_id": a.actor_id,
            "state": a.state,
            "addr": a.addr,
            "incarnation": a.incarnation,
            "name": a.name,
            "death_cause": a.death_cause,
            "node_id": a.node_id,
            # the hosting worker: what `ca profile <actor>` resolves through
            # (and how list_actors() users find the process to inspect)
            "worker_id": a.worker_id,
            "method_options": a.method_options,
        }

    async def _on_worker_death(self, rec: WorkerRec):
        if rec.state == "dead":
            return
        prev_state = rec.state
        rec.state = "dead"
        self._log_event(
            "worker_died", worker_id=rec.worker_id, prev_state=prev_state, node_id=rec.node_id
        )
        fut = self._register_waiters.pop(rec.worker_id, None)
        if fut is not None and not fut.done():
            fut.set_result(False)
        conn = self._worker_conns.pop(rec.worker_id, None)
        if conn is not None:
            fence_close_conn(conn)
        # fence the worker: close its registration connection so a live-but-
        # declared-dead process exits instead of acting on stale leases.
        # Under an active blackhole both closes defer until the link heals —
        # a partition delivers no FIN; the zombie instead learns its verdict
        # at heal (refused re-register / FencedError on its stamped RPCs).
        client_state = self._clients.get(rec.worker_id)
        if client_state is not None:
            fence_close(client_state["writer"])
        node = self.nodes.get(rec.node_id)
        if node is not None:
            try:
                node.idle[rec.pool].remove(rec.worker_id)
            except ValueError:
                pass
        if rec.tpu_chip is not None:
            if self._chip_alloc is not None:
                self._chip_alloc.release(rec.tpu_chip)
            rec.tpu_chip = None
        if rec.blocked:
            # its cpus were returned to the pool at block time; take them back
            # before the lease/actor release re-adds them (double-free guard)
            shape = None
            if rec.lease_id:
                shape = self._lease_shapes.get(rec.lease_id)
            elif rec.actor_id and rec.actor_id in self.actors:
                shape = self.actors[rec.actor_id].resources
            elif prev_state == "delegated":
                # agent-granted lease blocked in get(): the blocked release
                # was the slot's unit charge (_blocked_shape_node) — take it
                # back here or the delegated credit below over-credits the
                # node by one unit per blocked-death
                shape = LEASE_UNIT_SHAPES.get(rec.pool)
            cpus = (shape or {}).get("CPU", 0.0)
            if cpus and node is not None and node.up:
                self._take(node.avail, {"CPU": cpus})
            rec.blocked = False
        if prev_state == "delegated":
            # the slot's unit charge returns to the node (the agent reaps the
            # process itself and shrinks its block; any outstanding local
            # grant dies with the worker — submitters see the broken
            # connection and retry on a fresh lease)
            node2 = self.nodes.get(rec.node_id)
            if node2 is not None:
                self._undelegate_wid(node2, rec.pool, rec.worker_id, dead=True)
        if rec.lease_id:
            self._release_lease(rec.lease_id, worker_ok=False)
        if rec.actor_id:
            a = self.actors.get(rec.actor_id)
            if a is not None and a.state in ("alive", "restarting", "pending"):
                # return the actor's lifetime resources to wherever they were
                # charged; a PG-charged actor whose PG is already removed
                # credits nothing (the reservation went back with the PG)
                if a.charged == "pg":
                    if a.pg_id in self.pgs:
                        b = self.pgs[a.pg_id].bundles[a.bundle_index]
                        for k, v in a.resources.items():
                            b.used[k] = b.used.get(k, 0.0) - v
                elif a.charged == "node":
                    anode = self.nodes.get(a.node_id or LOCAL_NODE)
                    if anode is not None and anode.up:
                        self._give(anode.avail, a.resources)
                a.charged = None
                if a.can_restart:
                    a.restarts_used += 1
                    a.incarnation += 1
                    a.state = "restarting"
                    a.addr = None
                    self.stats["actor_restarts"] += 1
                    self._log_event("actor_restarting", actor_id=a.actor_id, attempt=a.restarts_used)
                    self._pub("actors", self._actor_info(a))

                    async def _restart(a=a):
                        await asyncio.sleep(self.config.actor_restart_backoff_s)
                        await self._place_actor(a)

                    # BACKGROUND, never awaited here: _on_worker_death runs
                    # on the monitor loop, and a restart placement can block
                    # up to worker_register_timeout_s against a node that is
                    # silently partitioned — wedging the very failure
                    # detector that would declare that node dead.  (Observed:
                    # an actor restart aimed at a blackholed node froze node
                    # death detection for 30s.)
                    spawn_bg(_restart())
                else:
                    a.state = "dead"
                    a.death_cause = a.death_cause or "actor worker died"
                    self._log_event("actor_dead", actor_id=a.actor_id, cause=a.death_cause)
                    self._drop_actor_name(a)
                    self._pub("actors", self._actor_info(a))
        self._service_queue()

    def _drop_actor_name(self, a: ActorRec):
        if a.name and self.named_actors.get(a.name) == a.actor_id:
            del self.named_actors[a.name]

    # ---------------------------------------------------------------- nodes
    async def _connect_agent(self, node: NodeRec):
        from ..util.aio import dial  # lazy: util/__init__ reaches into core

        try:
            node.conn = await dial(
                node.addr, purpose=f"agent {node.node_id}",
                peer_node=node.node_id,
            )
            # head->agent calls carry the authority epoch: after a failover
            # the agent fences any call still arriving from the OLD head
            node.conn.stamp = {"hep": self.head_epoch}
        except asyncio.CancelledError:
            raise  # head shutdown: must not declare the node dead
        except Exception as e:
            self._log_event("agent_connect_failed", node_id=node.node_id, error=repr(e))
            await self._on_node_death(node)

    async def _on_node_death(self, node: NodeRec):
        """Node agent died or went silent: everything on it is gone.
        Mirrors GcsNodeManager::OnNodeFailure + per-manager node-death hooks."""
        if node.state in ("dead", "drained"):
            # a drained node's agent exiting is the PLANNED end of the drain
            # FSM — its tables were already settled by _drain_finalize
            return
        node.state = "dead"
        self._drain_evac_done.discard(node.node_id)  # died mid-drain
        self.stats["nodes_died"] += 1
        self._log_event("node_died", node_id=node.node_id)
        if node.conn is not None:
            fence_close_conn(node.conn)
            node.conn = None
        node.lease_used = {}  # stale agent-reported occupancy
        for key in [k for k in self._pending_block_adopt if k[0] == node.node_id]:
            del self._pending_block_adopt[key]
        # fence the agent: close its registration connection so an agent
        # declared dead by heartbeat timeout tears itself down (kills its
        # workers, sweeps its shm namespace) instead of zombieing on.
        # Deferred while a blackhole covers the link (no FIN through a
        # partition): the healed agent discovers the verdict via FencedError
        # on its next stamped RPC or refused re-register, then purges and
        # rejoins at a fresh incarnation.
        agent_state = self._clients.get(node.node_id)
        if agent_state is not None:
            fence_close(agent_state["writer"])
        # workers on the node are dead (their lease/actor cleanup runs through
        # the normal worker-death path; node.avail credits are skipped because
        # the node is already marked dead)
        for rec in list(self.workers.values()):
            if rec.node_id == node.node_id and rec.state != "dead":
                await self._on_worker_death(rec)
        # objects: promote a surviving copy to primary, else the object is
        # lost (locate -> not found -> ObjectLostError / reconstruction)
        for rec in list(self.objects.values()):
            rec.copies.pop(node.node_id, None)
            if rec.node_id == node.node_id:
                if rec.copies:
                    new_node, new_name = next(iter(rec.copies.items()))
                    rec.node_id, rec.shm_name = new_node, new_name
                    del rec.copies[new_node]
                else:
                    self.objects.pop(rec.oid, None)
                    self._log_event("object_lost", oid=rec.oid.hex(), node_id=node.node_id)
        # placement groups: bundles on the dead node lose their reservation
        # and the PG goes back to pending for re-placement (reference:
        # GcsPlacementGroupManager::OnNodeDead reschedules)
        for pg in self.pgs.values():
            hit = False
            for b in pg.bundles:
                if b.node_id == node.node_id:
                    b.node_id = None
                    b.used = {}
                    hit = True
            if hit and pg.state == "created":
                pg.state = "pending"
                self.pending_pgs.append(pg.pg_id)
                self._log_event("pg_rescheduling", pg_id=pg.pg_id)
        self._pub("nodes", {"node_id": node.node_id, "alive": False})
        self._service_queue()

    # ----------------------------------------------------------- drain plane
    # FSM: alive -> draining -> drained (DrainNode protocol analogue,
    # gcs_node_manager.h HandleDrainNode).  A drain converts an announced
    # exit (preemption warning, autoscaler downscale, `ca drain`) into
    # zero-loss evacuation: placement stops immediately, delegated lease
    # blocks are recalled, actors restart on survivors through the normal
    # restart FSM (without consuming their restart budget), sole-copy
    # primary objects re-replicate, and running tasks get until the deadline
    # before the kill — whose retries clients exempt from max_retries.

    DRAIN_REASONS = ("preemption", "idle", "manual")

    # ------------------------------------------------------ net-chaos plane
    async def _h_net_chaos(self, state, msg, reply, reply_err):
        """Install (or clear, spec="") a network-chaos schedule cluster-wide:
        the head applies it locally and broadcasts it to every connected
        client (workers, drivers, agents — agents' registration conns are
        clients too), so all processes drop/delay the same links from the
        same seeded schedule.  Scheduled windows (blackhole@S+D, flap) are
        the way to inject a PARTITION: the heal must come from the schedule,
        because a `clear` broadcast cannot reach a process it partitioned.
        Status-only callers omit `spec`."""
        if "spec" in msg:
            spec = msg.get("spec") or ""
            # one shared anchor for every process's window offsets: default
            # it HERE so late joiners and rebroadcasts agree with the
            # original installation instead of re-opening healed windows
            epoch = msg.get("epoch")
            if epoch is None:
                epoch = time.time()
            try:
                netchaos.install(spec, LOCAL_NODE, epoch=epoch)
            except (ValueError, TypeError) as e:
                reply_err(e)
                return
            self._net_chaos_spec = spec
            self._net_chaos_epoch = epoch if spec else None
            self._log_event("net_chaos", spec=spec)
            frame = {"m": "net_chaos", "spec": spec, "epoch": epoch}
            for st in list(self._clients.values()):
                try:
                    write_frame(st["writer"], frame)
                except Exception:
                    pass
        reply(spec=self._net_chaos_spec, status=netchaos.status())

    async def _h_drain_node(self, state, msg, reply, reply_err):
        nid = msg.get("node_id")
        node = self.nodes.get(nid)
        if node is None:
            reply_err(ValueError(f"unknown node {nid!r}"))
            return
        if node.is_local:
            reply_err(ValueError(
                "cannot drain the head node n0 (stop the cluster instead)"
            ))
            return
        if node.state != "alive":
            reply(state=node.state)  # idempotent: already draining/gone
            return
        reason = msg.get("reason") or "manual"
        if reason not in self.DRAIN_REASONS:
            reply_err(ValueError(
                f"drain reason must be one of {self.DRAIN_REASONS}, got {reason!r}"
            ))
            return
        raw = msg.get("deadline_s")
        # explicit 0 is a valid "drain NOW" — only None takes the default
        deadline_s = float(self.config.drain_deadline_s if raw is None else raw)
        self._drain_begin(node, reason, deadline_s)
        reply(state="draining", deadline_s=deadline_s)

    def _drain_begin(self, node: NodeRec, reason: str, deadline_s: float):
        node.state = "draining"
        node.drain_reason = reason
        node.drain_deadline = time.monotonic() + deadline_s
        key = f"drain_nodes_{reason}"
        self.stats[key] = self.stats.get(key, 0) + 1
        self._log_event(
            "node_draining", node_id=node.node_id, reason=reason,
            deadline_s=deadline_s,
        )
        # recall the delegated lease blocks: unleased slots come back now;
        # outstanding local grants keep their workers until the deadline
        if node.conn is not None and not node.conn.closed:
            for pool, wids in node.delegated.items():
                if wids:
                    try:
                        node.conn.notify("lease_block_revoke", pool=pool, n=len(wids))
                    except Exception:
                        pass
        # PG bundles reserved here lose their reservation and the PG goes
        # back to pending for placement on survivors (node-death semantics,
        # but the capacity is credited back — the node is still accounted
        # while draining)
        for pg in self.pgs.values():
            hit = False
            for b in pg.bundles:
                if b.node_id == node.node_id:
                    self._give(node.avail, b.resources)
                    b.node_id = None
                    b.used = {}
                    hit = True
            if hit:
                # actors charged against the wiped reservations went back
                # WITH them (b.used reset): drop their charge marker, or the
                # migrate/finalize charge-return would decrement the re-placed
                # bundle's fresh accounting negative (permanent overcommit)
                for a in self.actors.values():
                    if (
                        a.pg_id == pg.pg_id
                        and a.charged == "pg"
                        and a.node_id == node.node_id
                    ):
                        a.charged = None
                if pg.state == "created":
                    pg.state = "pending"
                    self.pending_pgs.append(pg.pg_id)
                    self._log_event("pg_rescheduling", pg_id=pg.pg_id)
        # tell every client: task deaths on this node inside the window are
        # preemptions — retried without consuming the user's max_retries
        self._pub_drain(node)
        self._pub(
            "nodes", {"node_id": node.node_id, "alive": True, "state": "draining"}
        )
        self._drain_evac_done.discard(node.node_id)
        spawn_bg(self._drain_evacuate(node))
        self._dirty = True
        self._service_queue()

    def _drain_pub_frame(self, node: NodeRec) -> dict:
        """The one definition of the drain announcement (broadcast AND the
        register-time late-joiner push read it — they must never drift)."""
        return {
            "m": "pub",
            "ch": "drain",
            "data": {
                "node_id": node.node_id,
                "reason": node.drain_reason,
                "state": node.state,
                "deadline_s": max(0.0, node.drain_deadline - time.monotonic()),
            },
        }

    def _pub_drain(self, node: NodeRec):
        """Fan a drain announcement out to every connected client (drivers
        and workers both submit tasks).  Direct push, not channel pubsub:
        clients must not need a subscription round-trip to learn their
        retries are about to be free."""
        frame = self._drain_pub_frame(node)
        for st in list(self._clients.values()):
            try:
                write_frame(st["writer"], frame)
            except Exception:
                pass

    async def _drain_evacuate(self, node: NodeRec):
        """Background evacuation pass: re-home sole-copy primary objects
        FIRST, then migrate live actors off the node through the restart
        FSM.  Objects go first because they are bounded data moves, while
        an actor migration may legitimately WAIT for capacity (survivors'
        CPUs briefly all leased to evacuating tasks) — object safety must
        not sit behind that wait and lose the race with the deadline.
        Finishing arms the quiesce check in the monitor loop."""
        try:
            await self._evacuate_objects(node)
            for a in list(self.actors.values()):
                if node.state != "draining":
                    return
                if a.node_id == node.node_id and a.state == "alive":
                    if not a.drain_migration:
                        # supervisor-managed (serve replicas): the owner
                        # drains it app-aware; the deadline kill still
                        # applies if the supervisor doesn't finish in time
                        continue
                    await self._migrate_actor(a, node)
        except asyncio.CancelledError:
            raise  # the finally still arms/skips the quiesce check
        except Exception as e:
            self._log_event(
                "drain_evacuate_failed", node_id=node.node_id, error=repr(e)
            )
        finally:
            if node.state == "draining":
                # arm the quiesce check — unless the node died or finalized
                # mid-pass, where adding would leak a stale id in the set
                self._drain_evac_done.add(node.node_id)

    async def _migrate_actor(self, a: ActorRec, node: NodeRec):
        """Proactively restart one actor on a survivor (drain evacuation).
        Rides the normal restart FSM (clients see restarting -> alive and
        re-resolve the address) but does NOT consume restarts_used: a drain
        is a system event, not an app failure."""
        old_rec = self.workers.get(a.worker_id) if a.worker_id else None
        # return the old incarnation's charge to wherever it was taken
        if a.charged == "pg":
            if a.pg_id in self.pgs:
                b = self.pgs[a.pg_id].bundles[a.bundle_index]
                for k, v in a.resources.items():
                    b.used[k] = b.used.get(k, 0.0) - v
        elif a.charged == "node":
            anode = self.nodes.get(a.node_id or LOCAL_NODE)
            if anode is not None and anode.up:
                self._give(anode.avail, a.resources)
        a.charged = None
        a.incarnation += 1
        a.state = "restarting"
        a.addr = None
        self.stats["drain_actors_migrated"] += 1
        self.stats["actor_restarts"] += 1
        self._log_event(
            "actor_migrating", actor_id=a.actor_id, from_node=node.node_id
        )
        self._pub("actors", self._actor_info(a))
        if old_rec is not None:
            # detach BEFORE the kill: the old worker's death event must not
            # re-fire the restart FSM against the new incarnation
            old_rec.actor_id = None
            self._kill_worker_rec(old_rec)
        await self._place_actor(a)

    async def _evacuate_objects(self, node: NodeRec):
        """Re-home every primary copy whose only holder is the draining
        node: promote an existing survivor copy when one exists, else pull
        the bytes into the head's n0 namespace (obj_copy/spill machinery in
        reverse — the head is always a valid transfer target).  After this,
        an announced exit can never fire ObjectLostError/reconstruction."""
        for rec in list(self.objects.values()):
            if node.state != "draining":
                return
            if rec.node_id != node.node_id or rec.oid not in self.objects:
                continue
            if self._promote_copy(rec):
                self.stats["drain_objects_migrated"] += 1
                continue
            await self._pull_object_to_head(node, rec)

    def _promote_copy(self, rec: ObjectRec) -> bool:
        """Make an existing copy on a schedulable survivor the primary.  The
        old primary's bytes stay on the draining node untracked — its whole
        shm namespace is swept when the agent terminates."""
        for nid in list(rec.copies):
            n2 = self.nodes.get(nid)
            if n2 is not None and n2.state == "alive":
                rec.node_id = nid
                rec.shm_name = rec.copies.pop(nid)
                rec.spill_path = None
                rec.pending_free = None
                return True
        return False

    async def _pull_object_to_head(self, node: NodeRec, rec: ObjectRec):
        """Chunk-pull one object off the draining node into a dedicated n0
        segment and promote it to primary (the same wire path workers use
        for node-to-node transfer, served by the node's agent)."""
        if node.conn is None or node.conn.closed:
            return
        src = rec.shm_name or (f"spill:{rec.spill_path}" if rec.spill_path else None)
        if src is None:
            return
        name = f"{self.session_name}/{LOCAL_NODE}/drain_{rec.oid.hex()}"
        path = os.path.join("/dev/shm", name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        chunk = self.config.transfer_chunk_bytes
        window = max(1, int(getattr(self.config, "transfer_window", 4)))
        from collections import deque as _deque

        pending = _deque(
            (off, min(chunk, rec.size - off))
            for off in range(0, rec.size, chunk)
        )

        failed: list = []

        async def _lane(fd: int) -> None:
            # windowed evacuation: drain deadlines are real — the serial
            # ping-pong wasted most of the window on round-trip latency.
            # One lane's failure aborts the transfer, so siblings stop at
            # the flag instead of draining the rest of a doomed object.
            while pending and not failed:
                off, ln = pending.popleft()
                try:
                    r = await node.conn.call(
                        "pull_chunk", shm_name=src, off=off, len=ln,
                        timeout=30,
                    )
                    data = r["data"]
                    if len(data) != ln:
                        raise ConnectionError("short read evacuating object")
                except BaseException as e:
                    failed.append(e)
                    raise
                os.pwrite(fd, data, off)  # out-of-order completions are fine

        try:
            fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
            try:
                if rec.size:
                    os.ftruncate(fd, rec.size)
                    # return_exceptions: every lane must settle before the
                    # fd closes (a plain gather leaves siblings pwriting a
                    # closed fd after the first failure)
                    results = await asyncio.gather(
                        *(_lane(fd) for _ in range(min(window, len(pending)))),
                        return_exceptions=True,
                    )
                    for e in results:
                        if isinstance(e, BaseException):
                            raise e
            finally:
                os.close(fd)
        except asyncio.CancelledError:
            try:
                os.unlink(path)  # don't leak the partial segment either way
            except OSError:
                pass
            raise
        except Exception as e:
            try:
                os.unlink(path)
            except OSError:
                pass
            self._log_event(
                "drain_object_evac_failed", oid=rec.oid.hex(),
                node_id=node.node_id, error=repr(e),
            )
            return
        if rec.oid not in self.objects or rec.node_id != node.node_id:
            # freed or re-homed while the pull ran: drop the orphan bytes
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        rec.node_id = LOCAL_NODE
        rec.shm_name = name
        rec.spill_path = None
        rec.pending_free = None
        self.stats["drain_objects_migrated"] += 1
        self.stats["objects_transferred"] += 1

    def _drain_quiesced(self, node: NodeRec) -> bool:
        """Evacuation finished and nothing is still running on the node —
        the drain can complete before its deadline."""
        if node.node_id not in self._drain_evac_done:
            return False
        for w in self.workers.values():
            if w.node_id == node.node_id and w.state in ("leased", "actor"):
                return False
        # agent-granted local leases (heartbeat-fed block occupancy)
        for hb in node.lease_used.values():
            if int((hb or {}).get("used", 0)) > 0:
                return False
        return True

    async def _drain_finalize(self, node: NodeRec):
        """Deadline reached or the node quiesced: the drain completes.  Any
        still-busy workers are deadline kills (their submitters retry for
        free), the worker table settles through the normal death path, and
        the agent is told to shut down so the provider can reclaim the VM."""
        if node.state != "draining":
            return
        busy = sum(
            1
            for w in self.workers.values()
            if w.node_id == node.node_id and w.state in ("leased", "actor")
        )
        busy += sum(
            int((hb or {}).get("used", 0)) for hb in node.lease_used.values()
        )
        if busy:
            self.stats["drain_deadline_kills"] += busy
        node.state = "drained"
        self.stats["nodes_drained"] += 1
        self._drain_evac_done.discard(node.node_id)
        self._log_event(
            "node_drained", node_id=node.node_id, reason=node.drain_reason,
            deadline_kills=busy,
        )
        # residual primaries (evacuation raced a new put, or a pull failed):
        # promote a survivor copy, else the object is genuinely lost
        for rec in list(self.objects.values()):
            rec.copies.pop(node.node_id, None)
            if rec.node_id == node.node_id:
                if not self._promote_copy(rec):
                    self.objects.pop(rec.oid, None)
                    self._log_event(
                        "object_lost", oid=rec.oid.hex(), node_id=node.node_id
                    )
        # the no-budget retry window must outlive the kills below
        self._pub_drain(node)
        for rec in list(self.workers.values()):
            if rec.node_id == node.node_id and rec.state != "dead":
                await self._on_worker_death(rec)
        # the agent tears itself down (kills workers, sweeps shm, exits);
        # providers watching for `drained` may now terminate the VM
        if node.conn is not None and not node.conn.closed:
            try:
                node.conn.notify("node_shutdown")
            except Exception:
                pass
        self._pub("nodes", {"node_id": node.node_id, "alive": False, "state": "drained"})
        self._dirty = True
        self._service_queue()

    # --------------------------------------------------------------- objects
    def _free_shm_name(self, shm_name: str, node_id: str):
        """Release one physical copy: arena slices are reclaimed by their
        creating process's allocator (pubsub), dedicated segments unlinked on
        the node that holds them (locally for n0, via the agent otherwise)."""
        if "@" in shm_name:
            # arena slice: only the creating process's allocator can reclaim
            # it — parse the creator out of the arena file name,
            # .../arena_<client_id>_<seq>.
            fname = shm_name.split("@", 1)[0].rsplit("/", 1)[-1]
            cid = fname[len("arena_"): fname.rfind("_")]
            self._pub(f"shm_free:{cid}", {"shm_name": shm_name})
            return
        if node_id == LOCAL_NODE:
            drop_pull_map(self._pull_maps, shm_name)
            path = os.path.join("/dev/shm", shm_name)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        else:
            node = self.nodes.get(node_id)
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    node.conn.notify("unlink_shm", shm_name=shm_name)
                except Exception:
                    pass

    def _free_spill(self, path: str, node_id: str):
        if node_id == LOCAL_NODE:
            try:
                os.unlink(path)
            except OSError:
                pass
        else:
            node = self.nodes.get(node_id)
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    node.conn.notify("unlink_spill", path=path)
                except Exception:
                    pass

    def _early_ref_add(self, oid: bytes, holder: str) -> None:
        """Park a holder registration that raced ahead of obj_created
        (cross-socket ordering).  The grace window is EXPLICIT and bounded:
        the first add stamps the entry, and the monitor loop expires entries
        older than config.early_ref_grace_s — a producer that died before
        registering must not pin its early refs forever (and dict insertion
        order is no longer load-bearing for cleanup)."""
        e = self._early_refs.get(oid)
        if e is None:
            e = self._early_refs[oid] = set()
            self._early_ref_ts[oid] = time.monotonic()
        e.add(holder)

    def _take_early_refs(self, oid: bytes) -> set:
        """Adopt (and clear) the parked holders at obj_created time."""
        self._early_ref_ts.pop(oid, None)
        return self._early_refs.pop(oid, set())

    def _obj_maybe_gc(self, rec: ObjectRec):
        if rec.owner_released and not rec.holders:
            self.objects.pop(rec.oid, None)
            self.stats["objects_gc"] += 1
            if rec.shm_name:
                self._free_shm_name(rec.shm_name, rec.node_id)
            if rec.pending_free:
                self._free_shm_name(rec.pending_free, rec.node_id)
            if rec.spill_path:
                self._free_spill(rec.spill_path, rec.node_id)
            for nid, name in rec.copies.items():
                self._free_shm_name(name, nid)
            if rec.contains:
                # release this object's containment pins on nested refs
                edge = f"cnt:{rec.oid.hex()}"
                for r in rec.contains:
                    inner = self.objects.get(r)
                    if inner is not None:
                        inner.holders.discard(edge)
                        self._obj_maybe_gc(inner)
            if rec.cnt_pairs:
                # owner-resident edges of a ledgerless (client-mode) owner's
                # container: route each dec to the ledger holding the pin
                self._release_cnt_pairs(
                    f"cnt:{rec.owner}:{rec.oid.hex()}", rec.cnt_pairs
                )
                rec.cnt_pairs = None

    # --------------------------------------------------------------- handler
    _READONLY_METHODS = frozenset(
        {
            "heartbeat", "node_heartbeat", "node_sync", "kv_get", "kv_keys",
            "get_function",
            "obj_locate", "pull_chunk", "nodes", "cluster_resources", "stats",
            "client_addr", "lease_dir",
            "list_actors", "list_workers", "list_task_events", "list_objects",
            "metrics_snapshot", "autoscaler_state", "list_pgs", "pg_wait",
            "get_actor", "task_events", "metrics_report", "flightrec",
            "log_sub", "log_batch", "log_fetch", "timeseries", "profile",
            "ha_status", "head_replicate", "head_replicate_ack",
            "head_promote",
        }
    )

    # head dispatch latency: fine-grained low end (the hot handlers are
    # tens of µs; the knee shows up as mass shifting right)
    _DISPATCH_BOUNDS = [
        1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0,
    ]
    _INFLIGHT_BOUNDS = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0]

    def _self_hist_observe(
        self, name: str, desc: str, bounds, value: float, tags_key: str
    ) -> None:
        """Observe into a histogram owned BY the head (this process has no
        metric flusher — it writes the aggregation table directly, so the
        series flows to /metrics, snapshots, and the time-series store like
        any shipped metric)."""
        rec = self.metrics.get(name)
        if rec is None:
            rec = self.metrics[name] = {
                "type": "histogram", "desc": desc, "data": {}
            }
        cur = rec["data"].get(tags_key)
        if cur is None:
            cur = rec["data"][tags_key] = {
                "buckets": [0] * (len(bounds) + 1), "sum": 0.0, "count": 0,
                "bounds": list(bounds),
            }
        import bisect

        cur["buckets"][bisect.bisect_left(bounds, value)] += 1
        cur["sum"] += value
        cur["count"] += 1

    def _self_gauge_set(self, name: str, desc: str, value: float) -> None:
        rec = self.metrics.get(name)
        if rec is None:
            rec = self.metrics[name] = {"type": "gauge", "desc": desc, "data": {}}
        rec["data"]["[]"] = float(value)

    def _method_tags_key(self, m: str) -> str:
        tk = self._self_tags_keys.get(m)
        if tk is None:
            tk = self._self_tags_keys[m] = json.dumps([["method", m]])
        return tk

    def _fence_refuse(self, state, msg, reply_err, nid, inc) -> None:
        """Refuse an RPC minted under a dead/superseded node incarnation.

        Requests get a FencedError reply; notifies (no "i") are dropped.
        Either way the sender is told via a `fenced` push frame, so a zombie
        that only ever notifies (heartbeats, ledger syncs) still learns its
        death verdict at heal time and can cancel its leases/tasks instead
        of completing duplicate side effects."""
        self.stats["fenced_rpcs"] = self.stats.get("fenced_rpcs", 0) + 1
        self._log_event(
            "rpc_fenced", method=msg.get("m"), node_id=nid, inc=inc,
            client_id=state.get("client_id"),
        )
        try:
            write_frame(state["writer"], {"m": "fenced", "node_id": nid, "ninc": inc})
        except Exception:
            pass
        if msg.get("i") is not None:
            node = self.nodes.get(nid)
            reply_err(FencedError(
                f"node {nid!r} incarnation {inc} was declared dead and its "
                f"state adopted (current: "
                f"{node.incarnation if node else 'unregistered'}); cancel "
                f"outstanding leases/tasks, tear down, and rejoin fresh"
            ))

    async def _handle(self, state, msg, reply, reply_err):
        m = msg["m"]
        h = getattr(self, "_h_" + m, None)
        if h is None:
            reply_err(ValueError(f"unknown head method {m}"))
            return
        # head-epoch authority gate (HA plane) — the node-incarnation fence
        # below, generalized to the head itself.  Ordering matters: learn of
        # a successor (demote) BEFORE refusing anything, and refuse
        # non-active roles BEFORE stale-stamp clients, so a standby/zombie
        # never executes an authority-bearing handler.
        hep = msg.get("hep")
        if hep is not None and hep > self.head_epoch:
            # a peer proves a successor head was promoted past us: THIS
            # process is the zombie — demote before touching any table
            self._ha_demote(hep, via=f"rpc:{m}")
        if self.ha_role != "active" and m not in self._HA_PASSIVE_METHODS:
            self._ha_refuse(state, msg, reply_err)
            return
        if hep is not None and hep < self.head_epoch and m != "register":
            # an RPC stamped under a superseded head epoch: make the sender
            # re-register (adopting the current epoch) before any
            # authority-bearing side effect can land
            self._ha_refuse(state, msg, reply_err, stale_client=True)
            return
        # incarnation fence: authority-bearing RPCs from workers/agents are
        # stamped with their node's incarnation (Connection.stamp / agent
        # fields); a stamp that no longer matches the node table means the
        # head declared that node dead and adopted its state — refuse before
        # dispatch so no stale-authority side effect (grant use, ledger
        # write, object/task report, KV commit) can land.  register is
        # exempt: its own dead-worker/stale-agent logic issues the verdict.
        inc = msg.get("ninc")
        if inc is not None and m != "register":
            nid = msg.get("node_id") or state.get("node_id")
            node = self.nodes.get(nid) if nid else None
            if node is None or node.state == "dead" or node.incarnation != inc:
                self._fence_refuse(state, msg, reply_err, nid, inc)
                return
        self.rpc_counts[m] += 1
        if m not in self._READONLY_METHODS:
            self._dirty = True  # persisted by the debounced snapshot loop
            self._repl_dirty = True  # replicated by the next HA delta tick
        tk = self._method_tags_key(m)
        self._dispatch_inflight += 1
        self._self_hist_observe(
            "ca_head_dispatch_inflight",
            "handlers in flight on the head loop when each RPC dispatched "
            "(queue-depth proxy), by method",
            self._INFLIGHT_BOUNDS, float(self._dispatch_inflight), tk,
        )
        t0 = time.perf_counter()
        try:
            await h(state, msg, reply, reply_err)
        except FencedError as e:
            # one of OUR outbound calls (made from inside the handler) was
            # epoch-fenced by an agent or successor head: a newer authority
            # exists somewhere — demote instead of retrying as a zombie.
            # Incarnation fences (node-scoped) pass through untouched.
            if "head epoch" in str(e):
                self._ha_demote(None, via=f"handler:{m}")
            reply_err(e)
        finally:
            self._dispatch_inflight -= 1
            self._self_hist_observe(
                "ca_head_dispatch_seconds",
                "head handler dispatch latency by RPC method",
                self._DISPATCH_BOUNDS, time.perf_counter() - t0, tk,
            )

    async def _h_register(self, state, msg, reply, reply_err):
        role = msg["role"]
        client_id = msg["client_id"]
        state["client_id"] = client_id
        state["role"] = role
        self._clients[client_id] = state
        if role == "agent":
            await self._register_agent(state, msg, reply, reply_err)
            return
        state["node_id"] = msg.get("node_id", LOCAL_NODE)
        # network-chaos labeling: this registration socket's peer lives on
        # that node — replies/pushes toward a partitioned node must drop
        netchaos.label_writer(state["writer"], state["node_id"])
        # remote (Ray-Client-analogue) drivers: they reach workers over TCP
        # only, and their node is a client-private namespace no one schedules
        # onto — worker/actor addresses handed to them must be the TCP duals
        state["remote"] = bool(msg.get("remote"))
        # every client gets its private shm-reclaim channel (arena slices can
        # only be freed by their owner's allocator)
        self.subscribers.setdefault(f"shm_free:{client_id}", []).append(state["writer"])
        if role == "driver":
            self._driver_clients.add(client_id)
            # actor address pubs (create/restart) keep the driver's
            # _actor_addr_cache warm.  Subscribed here, server-side, like the
            # shm_free channel: `ca lint` found the old client-side
            # `subscribe` RPC had no caller, so these pubs fanned out to
            # nobody and every driver paid a get_actor refresh per restart
            self.subscribers.setdefault("actors", []).append(state["writer"])
        if role in ("driver", "worker"):
            # node-death pubs: a PARTITIONED node's sockets never close by
            # themselves (frames just vanish), so every SUBMITTER — drivers
            # AND worker processes running nested tasks — needs the death
            # verdict pushed to fail its in-flight pushes over to survivors
            # (worker._on_node_dead_pub)
            self.subscribers.setdefault("nodes", []).append(state["writer"])
        self._departed_clients.pop(client_id, None)  # it's back: not dead
        if msg.get("addr") or msg.get("addr_tcp"):
            self.client_addrs[client_id] = {
                "addr": msg.get("addr") or "",
                "addr_tcp": msg.get("addr_tcp") or "",
                "node": state["node_id"],
            }
        if role == "worker":
            rec = self.workers.get(client_id)
            if rec is not None and rec.state == "dead":
                # fenced: a worker this head declared dead must not rejoin
                # (it may hold stale leases/actor state)
                reply_err(ConnectionError("worker was declared dead; exit"))
                return
            if rec is None:
                # externally started worker; register it on its node
                rec = WorkerRec(
                    client_id, msg.get("pid", 0), msg["addr"],
                    node_id=msg.get("node_id", LOCAL_NODE),
                )
                self.workers[client_id] = rec
            if msg.get("addr"):
                rec.addr = msg["addr"]
            if msg.get("addr_tcp"):
                rec.addr_tcp = msg["addr_tcp"]
            if msg.get("pid"):
                rec.pid = msg["pid"]
            rec.last_heartbeat = time.monotonic()
            if rec.purpose == "actor":
                rec.state = "actor"
                rec.busy_since = time.monotonic()
            elif rec.state in ("starting", "idle"):
                # leased workers reconnecting after a head restart keep their
                # lease; only fresh/idle ones (re)join the pool
                node = self.nodes.get(rec.node_id)
                pool_adopt = self._pending_block_adopt.pop(
                    (rec.node_id, client_id), None
                )
                if pool_adopt is not None and node is not None and node.state == "alive":
                    # the node's agent already holds this worker in a lease
                    # block (reported at its re-registration, before the
                    # worker re-registered here): adopt it as delegated —
                    # NOT idle — or both planes would grant it
                    self._take(node.avail, LEASE_UNIT_SHAPES[pool_adopt])
                    rec.state = "delegated"
                    node.delegated.setdefault(pool_adopt, set()).add(client_id)
                else:
                    rec.state = "idle"
                    if node is not None and node.state == "alive":
                        if client_id not in node.idle[rec.pool]:
                            node.idle[rec.pool].append(client_id)
            fut = self._register_waiters.pop(client_id, None)
            if fut is not None and not fut.done():
                fut.set_result(True)
            netchaos.register_addr(msg.get("addr"), rec.node_id)
            netchaos.register_addr(msg.get("addr_tcp"), rec.node_id)
            self._service_queue()
        extra = {}
        reg_node = self.nodes.get(state["node_id"])
        if reg_node is not None:
            # the client's node incarnation: workers stamp it onto every
            # authority-bearing RPC (Connection.stamp) so stale-incarnation
            # survivors of a partition are fenced, not believed
            extra["node_inc"] = reg_node.incarnation
        if self._net_chaos_spec:
            # a runtime-installed chaos schedule covers late joiners too —
            # with its ORIGINAL epoch, or healed windows would re-open
            extra["net_chaos"] = self._net_chaos_spec
            extra["net_chaos_epoch"] = self._net_chaos_epoch
        reply(
            node_id=state["node_id"],
            session=self.session_name,
            resources=self._agg_total(),
            head_tcp=self.tcp_addr,
            head_epoch=self.head_epoch,
            standbys=self._ha_standby_addrs(),
            **extra,
        )
        # late joiners learn about in-progress drains (their retries on those
        # nodes must be budget-exempt too)
        for node in self.nodes.values():
            if node.state == "draining":
                try:
                    write_frame(state["writer"], self._drain_pub_frame(node))
                except Exception:
                    pass

    async def _register_agent(self, state, msg, reply, reply_err):
        node_id = msg["client_id"]
        netchaos.label_writer(state["writer"], node_id)
        existing = self.nodes.get(node_id)
        reported_inc = msg.get("ninc")
        if (
            existing is not None
            and existing.state == "dead"
            and reported_inc is not None
        ):
            # a partitioned-then-healed agent re-registering with the token
            # of an incarnation this head already declared dead: deliver the
            # verdict.  The agent reacts by killing its (zombie) workers,
            # dropping every delegated block and local grant, sweeping its
            # shm namespace, and re-registering WITHOUT a token — which the
            # fresh-join path below accepts at a bumped incarnation.
            self.stats["fenced_rpcs"] = self.stats.get("fenced_rpcs", 0) + 1
            self._log_event(
                "agent_register_fenced", node_id=node_id, inc=reported_inc
            )
            reply_err(FencedError(
                f"node {node_id!r} incarnation {reported_inc} was declared "
                f"dead; purge local state (workers, lease blocks, shm) and "
                f"rejoin fresh"
            ))
            return
        if existing is not None and existing.up:
            if existing.conn is None or existing.conn.closed:
                # agent reconnecting to a restarted head: re-adopt in place
                # (resource accounting was restored from the snapshot)
                existing.addr = msg["addr"]
                existing.pid = msg.get("pid", existing.pid)
                existing.last_heartbeat = time.monotonic()
                existing.metrics_addr = msg.get("metrics_addr") or existing.metrics_addr
                state["node_id"] = node_id
                await self._connect_agent(existing)
                if not existing.up:
                    reply_err(ConnectionError(f"head cannot reach agent at {existing.addr}"))
                    return
                self._log_event("node_readopted", node_id=node_id)
                # local grants kept flowing while the head was down; adopt
                # the agent's authoritative block state before scheduling
                self._reconcile_lease_blocks(existing, msg.get("lease_blocks") or {})
                reply(
                    node_id=node_id, session=self.session_name,
                    head_tcp=self.tcp_addr, incarnation=existing.incarnation,
                    head_epoch=self.head_epoch,
                    standbys=self._ha_standby_addrs(),
                )
                self._service_queue()
                return
            reply_err(ValueError(f"node id {node_id!r} already registered"))
            return
        # fresh join (first registration, or a purged rejoin over a dead
        # record): mint a strictly increasing incarnation — larger than any
        # token this node id ever held, even across snapshotless restarts
        # (the agent reports its last token for exactly that reason)
        inc = max(
            self._node_incarnations.get(node_id, 0), int(reported_inc or 0)
        ) + 1
        self._node_incarnations[node_id] = inc
        node = self._add_node(
            NodeRec(
                node_id,
                msg["addr"],
                dict(msg.get("resources") or {}),
                dict(msg.get("resources") or {}),
                pid=msg.get("pid", 0),
                incarnation=inc,
                # the agent detects its own labels (its env, not the head's)
                labels={
                    **{str(k): str(v) for k, v in (msg.get("labels") or {}).items()},
                    "ca.io/node-id": node_id,
                },
            )
        )
        state["node_id"] = node_id
        node.metrics_addr = msg.get("metrics_addr") or None
        netchaos.register_addr(msg["addr"], node_id)
        self.stats["nodes_joined"] += 1
        self._log_event(
            "node_joined", node_id=node_id, resources=node.total,
            incarnation=inc,
        )
        await self._connect_agent(node)
        if node.state != "alive":
            # dial-back failed (unreachable advertised address): the join is
            # a failure, not a silent capacity loss
            reply_err(ConnectionError(f"head cannot reach agent at {node.addr}"))
            return
        if msg.get("lease_blocks"):
            # agent outlived a snapshotless head restart: its blocks are the
            # only record of the delegation
            self._reconcile_lease_blocks(node, msg["lease_blocks"])
        self._pub("nodes", {"node_id": node_id, "alive": True, "resources": node.total})
        extra = {}
        if self._net_chaos_spec:
            extra["net_chaos"] = self._net_chaos_spec
            extra["net_chaos_epoch"] = self._net_chaos_epoch
        reply(
            node_id=node_id, session=self.session_name,
            head_tcp=self.tcp_addr, incarnation=inc,
            head_epoch=self.head_epoch, standbys=self._ha_standby_addrs(),
            **extra,
        )
        self._service_queue()

    async def _h_node_heartbeat(self, state, msg, reply, reply_err):
        node = self.nodes.get(msg.get("node_id", state.get("node_id")))
        if node is not None:
            node.last_heartbeat = time.monotonic()
            if "mem_pressured" in msg:
                node.mem_pressured = bool(msg["mem_pressured"])
            if "load" in msg:
                node.load = msg["load"]
            if "lease_stats" in msg:
                # agent-side block occupancy (delegated vs used) for
                # `ca status` / /api/nodes / lease_dir freshness
                node.lease_used = msg["lease_stats"] or {}
            if "metrics" in msg:
                # metrics-plane piggyback: the node's queued worker deltas
                from ..util.metrics import merge_metric_records

                merge_metric_records(self.metrics, msg["metrics"])
            if "flightrec" in msg:
                self._ingest_flightrec(msg["flightrec"])

    async def _h_node_sync(self, state, msg, reply, reply_err):
        """Delta-synced node state (the ray_syncer analogue, head-ward):
        agents send versioned component deltas instead of full per-tick
        heartbeats.  A bare {node_id} frame is a keepalive (liveness only);
        components present in the frame replace the stored state; a frame
        with full=True replaces everything (reconnect resync).  The
        mem-pressure component carries a [flag, tick] pair while pressured
        so the kill policy's clear-after-acting re-arm keeps working."""
        node = self.nodes.get(msg.get("node_id", state.get("node_id")))
        if node is None:
            return
        node.last_heartbeat = time.monotonic()
        if "v" in msg:
            node.sync_version = msg["v"]
        if "load" in msg:
            node.load = msg["load"]
        if "lease_stats" in msg:
            node.lease_used = msg["lease_stats"] or {}
        if "mem_pressured" in msg:
            v = msg["mem_pressured"]
            node.mem_pressured = (
                bool(v[0]) if isinstance(v, (list, tuple)) else bool(v)
            )
        if "metrics" in msg:
            # metrics-plane piggyback: worker metric deltas the node's agent
            # queued since its last tick ride the sync frame — the head's
            # cluster table stays fed with ZERO standalone metric RPCs from
            # agent-node workers
            from ..util.metrics import merge_metric_records

            merge_metric_records(self.metrics, msg["metrics"])
        if "flightrec" in msg:
            # flight-recorder piggyback: the node's queued journal slices
            # (workers + agent) merge into the cluster ring the same way
            self._ingest_flightrec(msg["flightrec"])

    async def _h_owner_sync(self, state, msg, reply, reply_err):
        """An owner's ledger digest (versioned delta, or full on reconnect):
        what the head adopts if that owner dies.  Entries carry the borrower
        set ("b"), the owner-released flag ("r"), and whether the object is
        registered here ("g"); removed oids settle out of the digest."""
        cid = state.get("client_id", "?")
        digest = self.owner_digests.setdefault(cid, {})
        if msg.get("full"):
            digest.clear()
        for oid, info in (msg.get("e") or {}).items():
            digest[oid] = info
        for oid in msg.get("rm") or ():
            digest.pop(oid, None)

    async def _h_obj_release(self, state, msg, reply, reply_err):
        """An owner's ledger settled an object's cluster-wide lifetime (the
        registry half of ownership-plane GC): drop the record and reclaim
        whatever physical copies the owner could not free itself — it
        already freed its local slices/spill files and says so in `freed`,
        which must not be double-freed (arena slices get recycled)."""
        cid = state.get("client_id", "?")
        digest = self.owner_digests.get(cid)
        released = 0
        for pair in msg.get("rel") or ():
            oid, freed = pair[0], set(pair[1] or ())
            if digest is not None:
                digest.pop(oid, None)
            rec = self.objects.get(oid)
            if rec is None:
                # never registered (inline-only) or already reaped: drop any
                # stray early refs so they don't age out as "expired"
                if self._early_refs.pop(oid, None) is not None:
                    self._early_ref_ts.pop(oid, None)
                continue
            if rec.shm_name in freed:
                rec.shm_name = None
            if rec.pending_free in freed:
                rec.pending_free = None
            if rec.spill_path and ("spill:" + rec.spill_path) in freed:
                rec.spill_path = None
            # the owner is the lifetime authority: its settle overrides any
            # head-side holder residue (early strays, fallback pins)
            rec.owner_released = True
            rec.holders.clear()
            self._obj_maybe_gc(rec)
            released += 1
        if released:
            self.stats["objects_released_by_owner"] = (
                self.stats.get("objects_released_by_owner", 0) + released
            )

    async def _h_worker_exit(self, state, msg, reply, reply_err):
        """Node agent reports one of its worker processes exited."""
        rec = self.workers.get(msg["wid"])
        if rec is not None:
            await self._on_worker_death(rec)

    async def _h_heartbeat(self, state, msg, reply, reply_err):
        rec = self.workers.get(msg.get("client_id", state.get("client_id")))
        if rec is not None:
            rec.last_heartbeat = time.monotonic()

    async def _h_request_lease(self, state, msg, reply, reply_err):
        ttl = msg.get("ttl")
        req = LeaseReq(
            shape=msg.get("shape") or {"CPU": 1.0},
            reply=reply,
            reply_err=reply_err,
            client=state.get("client_id", "?"),
            pg_id=msg.get("pg_id"),
            bundle_index=msg.get("bundle_index", -1),
            strategy=msg.get("strategy"),
            remote=bool(state.get("remote")),
            deadline=(time.monotonic() + float(ttl)) if ttl else None,
        )
        if not self._try_grant(req):
            self.pending_leases.append(req)
            if req.deadline is None:
                self._last_central_demand = time.monotonic()
            self._ensure_pool()
            self._nudge_lease_holders(req.client)

    def _nudge_lease_holders(self, requester: str):
        """A lease request just queued while other clients hold leases:
        push a reclaim hint so holders return their IDLE leases now instead
        of after the 1s idle timeout.  Without this, concurrent client
        batches serialize with ~1s gaps (each waits out the previous
        holder's idle-return) — the multi-client aggregate collapse.
        Debounced: a queued burst nudges once per 100ms."""
        now = time.monotonic()
        if now - self._last_reclaim_nudge < 0.1:
            return
        self._last_reclaim_nudge = now
        holders = set(self._lease_client.values())
        parties = holders | {r.client for r in self.pending_leases}
        if requester:
            parties.add(requester)
        if len(parties) <= 1:
            # a single client contending with itself (e.g. SPREAD growth
            # waiting on cold nodes' workers to spawn) is not a fairness
            # problem — capping it would defeat the growth it is waiting for
            return
        n_workers = sum(
            1
            for w in self.workers.values()
            if w.purpose == "pool" and w.state in ("starting", "idle", "leased")
        )
        cap = max(1, n_workers // max(1, len(parties)))
        for cid in holders:
            if cid == requester:
                continue  # its own pools keep leases they still need
            state = self._clients.get(cid)
            if state is None:
                continue
            try:
                write_frame(
                    state["writer"],
                    {"m": "pub", "ch": "lease_reclaim", "data": {"cap": cap}},
                )
            except Exception:
                pass

    async def _h_return_lease(self, state, msg, reply, reply_err):
        for lid in msg["lease_ids"]:
            self._release_lease(lid)

    def _blocked_shape_node(self, rec: WorkerRec):
        shape = None
        if rec.lease_id:
            shape = self._lease_shapes.get(rec.lease_id)
        elif rec.actor_id and rec.actor_id in self.actors:
            shape = self.actors[rec.actor_id].resources
        elif rec.state == "delegated":
            # agent-granted lease: the head holds no per-lease record, but
            # the slot's unit charge is known — blocked-in-get() workers
            # release it so nested tasks can run (deadlock avoidance)
            shape = LEASE_UNIT_SHAPES.get(rec.pool)
        return shape, self.nodes.get(rec.node_id)

    async def _h_worker_blocked(self, state, msg, reply, reply_err):
        # a leased/actor worker blocked in get(): release its cpus so nested
        # tasks can run (deadlock avoidance, as the reference raylet does when
        # a worker blocks — local_task_manager ReleaseCpuResourcesFromBlockedWorker)
        wid = msg.get("client_id", state.get("client_id"))
        rec = self.workers.get(wid)
        if rec is not None and not rec.blocked:
            rec.blocked = True
            shape, node = self._blocked_shape_node(rec)
            cpus = (shape or {}).get("CPU", 0.0)
            if cpus and node is not None and node.up:
                self._give(node.avail, {"CPU": cpus})
                self._service_queue()

    async def _h_worker_unblocked(self, state, msg, reply, reply_err):
        wid = msg.get("client_id", state.get("client_id"))
        rec = self.workers.get(wid)
        if rec is not None and rec.blocked:
            rec.blocked = False
            shape, node = self._blocked_shape_node(rec)
            cpus = (shape or {}).get("CPU", 0.0)
            if cpus and node is not None and node.up:
                # oversubscribe temporarily rather than deadlock
                self._take(node.avail, {"CPU": cpus})

    async def _h_create_actor(self, state, msg, reply, reply_err):
        a = ActorRec(
            actor_id=msg["actor_id"],
            name=msg.get("name"),
            fn_id=msg["fn_id"],
            init_spec=msg["init_spec"],
            resources=msg.get("resources") or {},
            max_restarts=msg.get("max_restarts", 0),
            detached=msg.get("detached", False),
            max_concurrency=msg.get("max_concurrency", 1),
            concurrency_groups=msg.get("concurrency_groups"),
            method_options=msg.get("method_options"),
            pg_id=msg.get("pg_id"),
            bundle_index=msg.get("bundle_index", -1),
            runtime_env=msg.get("runtime_env"),
            strategy=msg.get("strategy"),
            drain_migration=msg.get("drain_migration", True),
        )
        if a.name:
            if a.name in self.named_actors:
                reply_err(ValueError(f"actor name {a.name!r} already taken"))
                return
            self.named_actors[a.name] = a.actor_id
        self.actors[a.actor_id] = a
        await self._place_actor(a)
        if a.state == "alive":
            reply(addr=self._actor_addr_for(a, state), incarnation=a.incarnation)
        else:
            self._drop_actor_name(a)
            reply_err(ActorDiedError(a.death_cause))

    def _actor_addr_for(self, a: ActorRec, state) -> Optional[str]:
        if state.get("remote") and a.worker_id:
            rec = self.workers.get(a.worker_id)
            if rec is not None and rec.addr_tcp:
                return rec.addr_tcp
        return a.addr

    async def _h_get_actor(self, state, msg, reply, reply_err):
        aid = msg.get("actor_id")
        if aid is None and msg.get("name") is not None:
            aid = self.named_actors.get(msg["name"])
            if aid is None:
                reply_err(ValueError(f"no actor named {msg['name']!r}"))
                return
        a = self.actors.get(aid)
        if a is None:
            reply_err(ValueError("actor not found"))
            return
        info = self._actor_info(a)
        info["fn_id"] = a.fn_id
        info["addr"] = self._actor_addr_for(a, state)
        reply(**info)

    async def _h_kill_actor(self, state, msg, reply, reply_err):
        a = self.actors.get(msg["actor_id"])
        if a is None:
            reply()
            return
        if msg.get("no_restart", True):
            a.max_restarts = 0
        a.death_cause = "killed via kill()"
        rec = self.workers.get(a.worker_id) if a.worker_id else None
        if rec is not None:
            self._kill_worker_rec(rec)
        reply()

    def _kill_worker_rec(self, rec: WorkerRec):
        if rec.proc is not None and rec.proc.poll() is None:
            try:
                os.kill(rec.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        elif rec.proc is None and rec.node_id == LOCAL_NODE and rec.pid:
            # re-adopted after head restart: no Popen handle, kill by pid
            try:
                os.kill(rec.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        elif rec.proc is None:
            node = self.nodes.get(rec.node_id)
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    node.conn.notify("kill_worker", wid=rec.worker_id)
                except Exception:
                    pass

    async def _h_actor_exited(self, state, msg, reply, reply_err):
        # graceful actor exit (__ray_terminate__ analogue): no restart
        a = self.actors.get(msg["actor_id"])
        if a is not None:
            a.max_restarts = 0
            a.death_cause = "actor exited"

    # KV ------------------------------------------------------------------
    async def _h_kv_put(self, state, msg, reply, reply_err):
        ns = self.kv.setdefault(msg.get("ns", ""), {})
        exists = msg["key"] in ns
        if not (msg.get("overwrite", True) is False and exists):
            ns[msg["key"]] = msg["value"]
            if self._repl_subs:
                # acked-commit guarantee: the reply below IS the ack the
                # client keys side effects off, so the commit must be
                # standby-resident (synchronously replicated) first
                await self._repl_commit(
                    {"t": "kv", "op": "put", "ns": msg.get("ns", ""),
                     "key": msg["key"], "value": msg["value"],
                     "overwrite": msg.get("overwrite", True)}
                )
        reply(added=not exists)

    async def _h_kv_get(self, state, msg, reply, reply_err):
        ns = self.kv.get(msg.get("ns", ""), {})
        reply(value=ns.get(msg["key"]))

    async def _h_kv_del(self, state, msg, reply, reply_err):
        ns_name = msg.get("ns", "")
        ns = self.kv.get(ns_name, {})
        deleted = 1 if ns.pop(msg["key"], None) is not None else 0
        if not ns and ns_name in self.kv:
            # drop emptied namespaces: per-op rendezvous namespaces
            # (collectives) would otherwise leave O(ops) empty dicts in
            # the KV and in every debounced snapshot
            del self.kv[ns_name]
        if deleted and self._repl_subs:
            await self._repl_commit(
                {"t": "kv", "op": "del", "ns": ns_name, "key": msg["key"]}
            )
        reply(deleted=deleted)

    async def _h_kv_keys(self, state, msg, reply, reply_err):
        ns = self.kv.get(msg.get("ns", ""), {})
        prefix = msg.get("prefix", "")
        reply(keys=[k for k in ns.keys() if k.startswith(prefix)])

    async def _h_register_function(self, state, msg, reply, reply_err):
        ns = self.kv.setdefault("__functions__", {})
        ns[msg["fn_id"]] = msg["blob"]
        reply()

    async def _h_get_function(self, state, msg, reply, reply_err):
        blob = self.kv.get("__functions__", {}).get(msg["fn_id"])
        if blob is None:
            reply_err(KeyError(f"function {msg['fn_id']!r} not registered"))
        else:
            reply(blob=blob)

    # pubsub ---------------------------------------------------------------
    # (the old `subscribe`/`publish` RPC handlers are gone: no call site ever
    # existed — rpc-dead-handler — and client-facing pubsub happens by
    # server-side subscription at register: shm_free:<cid> and `actors`)

    # log plane -------------------------------------------------------------
    async def _h_log_sub(self, state, msg, reply, reply_err):
        """Driver (un)subscribes to the cluster log stream.  Sent as a
        notify right after register when log_to_driver is on."""
        cid = state.get("client_id") or f"anon-{id(state)}"
        if msg.get("on", True):
            self._log_subs[cid] = state["writer"]
        else:
            self._log_subs.pop(cid, None)
        reply()

    async def _h_log_batch(self, state, msg, reply, reply_err):
        """A node agent shipped a batch of captured records: fan out to
        subscribed drivers (the GCS-pubsub leg of the log monitor path)."""
        self._forward_logs(msg.get("records") or [])

    def _forward_logs(self, records) -> None:
        if not records or not self._log_subs:
            return
        dead = []
        delivered = False
        for cid, writer in self._log_subs.items():
            try:
                buf = writer.transport.get_write_buffer_size()
            except Exception:
                buf = 0
            if buf > (4 << 20):
                # bounded buffers, not backpressure: a stalled subscriber
                # loses this batch rather than stalling capture or workers
                self.stats["log_lines_dropped"] += len(records)
                continue
            try:
                write_frame(writer, {"m": "log_batch", "records": records})
                delivered = True
            except Exception:
                dead.append(cid)
        for cid in dead:
            self._log_subs.pop(cid, None)
        if delivered:
            self.stats["log_lines_shipped"] += len(records)

    async def _log_tail_loop(self):
        """Tail the head node's own capture files (n0 workers + the head
        itself) and forward — the local-node twin of the agents' ship loop."""
        from ..util.logplane import LogTailer, node_log_dir

        tailer = LogTailer(
            node_log_dir(self.session_dir, LOCAL_NODE),
            max_records=self.config.log_ship_batch,
        )
        period = max(self.config.log_ship_interval_s, 0.05)
        while not self._shutdown.is_set():
            await asyncio.sleep(period)
            if not self._log_subs:
                continue  # offsets hold; a late subscriber gets the backlog
            try:
                records = tailer.poll()
            except Exception:
                continue
            if records:
                self._forward_logs(records)

    def _resolve_log_target(self, ident) -> Tuple[str, str]:
        """Resolve a worker/actor/task/node id (or "head"/None) to
        (node_id, file base name) for the query plane."""
        if not ident or ident == "head":
            return (LOCAL_NODE, "head")
        if ident in self.nodes:
            return (ident, "head" if ident == LOCAL_NODE else "agent")
        rec = self.workers.get(ident)
        if rec is None:
            a = self.actors.get(ident)
            if a is not None and a.worker_id:
                rec = self.workers.get(a.worker_id)
        if rec is None:
            # task id: newest attribution wins (retries may have moved it)
            for e in reversed(self.task_events):
                if e.get("task_id") == ident and e.get("worker_id"):
                    rec = self.workers.get(e["worker_id"])
                    break
        if rec is None:
            raise FileNotFoundError(
                f"no log found for {ident!r}: not a known worker/actor/task/"
                "node id (try `ca list workers`)"
            )
        return (rec.node_id, rec.worker_id)

    async def _log_fetch_data(self, ident, tail: int = 200, off=None,
                              structured: bool = False,
                              trace: Optional[str] = None) -> dict:
        """Read/tail a log wherever it lives: local files directly, other
        nodes through their agent's log_read RPC (no shared filesystem).
        `trace` filters to lines stamped with that trace id (log records
        carry the ambient span of the code that printed them) — it implies
        the structured JSONL read, since the raw capture has no stamps."""
        from ..util.logplane import node_log_dir, tail_file

        if trace:
            structured = True
        node_id, name = self._resolve_log_target(ident)
        if node_id == LOCAL_NODE:
            if structured:
                path = os.path.join(
                    node_log_dir(self.session_dir, LOCAL_NODE), f"{name}.jsonl"
                )
            else:
                # raw fd-redirect logs: head.log and head-spawned workers
                # live at the session root
                path = os.path.join(self.session_dir, f"{name}.log")
            try:
                data, new_off = tail_file(path, tail=tail, off=off)
            except (FileNotFoundError, OSError):
                raise FileNotFoundError(
                    f"no log for {ident!r} yet (expected at {path})"
                )
            if trace:
                data = self._filter_log_trace(data, trace)
            return {"data": data, "off": new_off, "node_id": node_id}
        node = self.nodes.get(node_id)
        if node is None or not node.up or node.conn is None or node.conn.closed:
            # RuntimeError, not ConnectionError: a pickled ConnectionError
            # would look like "head down" to head_call's reconnect retry loop
            raise RuntimeError(
                f"node {node_id!r} (owner of {ident!r}) is unreachable"
            )
        try:
            r = await node.conn.call(
                "log_read", name=name, tail=tail, off=off,
                structured=structured, timeout=10,
            )
        except (ConnectionError, asyncio.TimeoutError):
            raise RuntimeError(
                f"node {node_id!r} (owner of {ident!r}) stopped answering"
            )
        out = {"data": r["data"], "off": r["off"], "node_id": node_id}
        if trace:
            out["data"] = self._filter_log_trace(out["data"], trace)
        return out

    @staticmethod
    def _filter_log_trace(data: str, trace: str) -> str:
        """Keep only JSONL records stamped with this trace id."""
        kept = []
        for line in data.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (rec.get("trace") or {}).get("tid") == trace:
                kept.append(line)
        return "\n".join(kept) + ("\n" if kept else "")

    def _log_counter_totals(self) -> Dict[str, int]:
        """Cluster-wide ca_log_* capture counters summed from the metrics
        table (shared by `ca status` stats and the dashboard /api/logplane)."""
        out = {}
        for mname in (
            "ca_log_lines_total", "ca_log_bytes_total", "ca_log_dropped_total"
        ):
            rec = self.metrics.get(mname)
            out[mname] = (
                int(sum(rec["data"].values())) if rec and rec.get("data") else 0
            )
        return out

    async def _h_log_fetch(self, state, msg, reply, reply_err):
        try:
            out = await self._log_fetch_data(
                msg.get("id"),
                tail=int(msg.get("tail") or 200),
                off=msg.get("off"),
                structured=bool(msg.get("structured")),
                trace=msg.get("trace"),
            )
        except (FileNotFoundError, RuntimeError, ValueError) as e:
            reply_err(e)
            return
        reply(**out)

    # objects --------------------------------------------------------------
    # ---- remote-client object upload (Ray-Client analogue data path) ----
    # A remote driver's /dev/shm is invisible to the cluster, so its puts
    # stream here in chunks; the head hosts the bytes in its own n0
    # namespace and registers the object with the client as owner.

    async def _h_client_put_begin(self, state, msg, reply, reply_err):
        import mmap as _mmap

        oid = msg["oid"]
        size = int(msg["size"])
        if size > self.config.object_store_memory:
            # no spill path exists for client uploads: refuse anything the
            # head's store budget could never hold rather than filling
            # /dev/shm until the whole node falls over
            reply_err(ObjectStoreFullError(
                f"client put of {size} bytes exceeds the head's object store "
                f"budget ({self.config.object_store_memory})"
            ))
            return
        name = f"{self.session_name}/{LOCAL_NODE}/cput_{oid.hex()}"
        path = os.path.join("/dev/shm", name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
            m = _mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)
        state.setdefault("cput", {})[oid] = (name, m, size)
        reply(name=name)

    async def _h_client_put_chunk(self, state, msg, reply, reply_err):
        ent = state.get("cput", {}).get(msg["oid"])
        if ent is None:
            reply_err(ValueError("client_put_begin missing for this oid"))
            return
        _, m, _ = ent
        off = msg["off"]
        data = msg["data"]
        m[off : off + len(data)] = data
        reply()

    async def _h_client_put_seal(self, state, msg, reply, reply_err):
        oid = msg["oid"]
        ent = state.get("cput", {}).pop(oid, None)
        if ent is None:
            reply_err(ValueError("client_put_begin missing for this oid"))
            return
        name, m, size = ent
        m.close()
        existing = self.objects.get(oid)
        if existing is not None:
            if existing.shm_name and existing.shm_name != name:
                self._free_shm_name(existing.shm_name, existing.node_id)
            existing.shm_name = name
            existing.size = size
            existing.node_id = LOCAL_NODE
            existing.copies.clear()
        else:
            rec = ObjectRec(
                oid=oid,
                shm_name=name,
                size=size,
                owner=state.get("client_id", "?"),
                node_id=LOCAL_NODE,
            )
            rec.holders |= self._take_early_refs(oid)
            self.objects[oid] = rec
            self.stats["objects_created"] += 1
        reply(name=name)

    async def _h_obj_created(self, state, msg, reply, reply_err):
        oid = msg["oid"]
        existing = self.objects.get(oid)
        if existing is not None:
            # re-registration (lineage reconstruction re-ran the creating
            # task, or a second borrower promoted the same object): keep the
            # holders; adopt the new physical location, free the old one
            new_name = msg.get("shm_name")
            new_node = msg.get("node") or state.get("node_id", LOCAL_NODE)
            if existing.shm_name and existing.shm_name != new_name:
                self._free_shm_name(existing.shm_name, existing.node_id)
            existing.shm_name = new_name
            existing.size = msg.get("size", existing.size)
            existing.node_id = new_node
            existing.copies.clear()
            return
        rec = ObjectRec(
            oid=oid,
            shm_name=msg.get("shm_name"),
            size=msg.get("size", 0),
            # the submitter owns task returns; the connecting client owns puts
            owner=msg.get("owner") or state.get("client_id", "?"),
            node_id=msg.get("node") or state.get("node_id", LOCAL_NODE),
        )
        rec.holders |= self._take_early_refs(oid)
        self.objects[oid] = rec
        self.stats["objects_created"] += 1

    def _forward_to_owner(self, owner: str, frame: dict) -> bool:
        """Push a settlement frame to a live owner's ledger over its own head
        connection (the worker side serves owner_refs/owner_transit_done on
        that socket).  Only owners that run a ledger qualify — a synced
        digest is the proof (client-mode drivers never sync one).  Returns
        False when the owner is dead/ledgerless/unwritable: the caller keeps
        the central path, which is also the post-adoption authority."""
        if owner not in self.owner_digests:
            return False
        st = self._clients.get(owner)
        if st is None:
            return False
        try:
            write_frame(st["writer"], frame)
            return True
        except Exception:
            return False

    def _release_cnt_pairs(self, edge: str, pairs) -> None:
        """Release owner-resident containment edges held under `edge` for a
        container whose lifetime settled HERE (its owner has no ledger):
        each dec routes to the ledger that actually holds the pin — a live
        owner's, pushed over its own head connection (the worker side
        serves `owner_refs` on that socket), or this registry for
        head-resident/adopted inners."""
        for p in pairs:
            ioid, iowner = bytes(p[0]), p[1]
            if iowner and self._forward_to_owner(
                iowner, {"m": "owner_refs", "dec": [ioid], "as_id": edge}
            ):
                continue
            # head-resident inner (incl. one owned by a LEDGERLESS client —
            # the digest qualification inside _forward_to_owner refuses
            # those, whose serve_owner_refs would drop the dec), or a dead
            # owner whose ledger this registry adopted: settle centrally
            rec = self.objects.get(ioid)
            if rec is not None:
                rec.holders.discard(edge)
                self._obj_maybe_gc(rec)
            else:
                e = self._early_refs.get(ioid)
                if e is not None:
                    e.discard(edge)

    async def _h_obj_contains(self, state, msg, reply, reply_err):
        """Register containment edges: the object's payload embeds serialized
        ObjectRefs, which must outlive it (borrowing, reference_count.h).
        Two forms: the head-resident one (refs only — this registry adds
        `cnt:<container>` holders to inner records), and the ownership-plane
        `pairs` form from a LEDGERLESS owner (client mode), whose edges
        already live at each inner object's own authority under
        `cnt:<owner>:<container>` — the registry only remembers the pairs so
        it can release them when the container settles here."""
        rec = self.objects.get(msg["oid"])
        refs = msg.get("refs") or []
        pairs = msg.get("pairs")
        if rec is None:
            if pairs:
                # container already settled or never registered: nobody else
                # will release these edges
                cid = state.get("client_id", "?")
                self._release_cnt_pairs(
                    f"cnt:{cid}:{msg['oid'].hex()}", pairs
                )
            return  # container unknown (already GC'd): nothing to pin
        if pairs is not None:
            edge = f"cnt:{rec.owner}:{rec.oid.hex()}"
            if rec.cnt_pairs:
                # re-registration (e.g. reconstruction re-ran the creating
                # task): release the previous edges or the old inners leak
                self._release_cnt_pairs(edge, rec.cnt_pairs)
            rec.cnt_pairs = [[bytes(i), o] for i, o in pairs]
            return
        edge = f"cnt:{rec.oid.hex()}"
        if rec.contains:
            # re-registration (e.g. reconstruction re-ran the creating task):
            # release the previous edges or the old inner objects leak
            for r in rec.contains:
                inner = self.objects.get(r)
                if inner is not None:
                    inner.holders.discard(edge)
                    self._obj_maybe_gc(inner)
        rec.contains = list(refs)
        for r in refs:
            inner = self.objects.get(r)
            if inner is not None:
                inner.holders.add(edge)
            else:
                self._early_ref_add(r, edge)

    async def _h_transit_done(self, state, msg, reply, reply_err):
        """Receiver ack of in-transit borrowed refs: the receiver now holds
        its own registration; drop the sender's transit pin.  If the pin
        hasn't landed yet (different sockets), tombstone the token so the
        late pin is cancelled instead of leaking a permanent holder."""
        cid = state.get("client_id", "?")
        token = msg["token"]
        # register=False: the receiver could NOT consume the payload
        # (corrupt/unreadable) — drop the pin without recording the caller
        # as a holder it isn't
        register = msg.get("register", True)
        self._transit_pins.pop(token, None)
        seen = False
        for oid in msg.get("oids") or []:
            rec = self.objects.get(oid)
            if rec is not None:
                if token not in rec.holders and self._forward_to_owner(
                    rec.owner,
                    {
                        "m": "owner_transit_done", "token": token,
                        "oids": [oid], "cid": cid, "register": register,
                    },
                ):
                    # ack fallback for a pin living in the (alive) owner's
                    # ledger: settle it there — tombstone semantics and the
                    # borrower registration must land at the same authority
                    continue
                if register:
                    rec.holders.add(cid)
                if token in rec.holders:
                    seen = True
                    rec.holders.discard(token)
                self._obj_maybe_gc(rec)
            else:
                early = self._early_refs.get(oid)
                if early is not None:
                    if register:
                        early.add(cid)
                    if token in early:
                        seen = True
                        early.discard(token)
                elif register:
                    self._early_ref_add(oid, cid)
        if not seen:
            self._spent_transit[token] = time.monotonic()

    async def _h_obj_copy(self, state, msg, reply, reply_err):
        """A node finished pulling a copy of an object (node-to-node
        transfer): record the secondary location.  Redundant copies (two
        workers on one node raced the same pull) are freed immediately rather
        than silently overwritten — only one copy per node is tracked."""
        rec = self.objects.get(msg["oid"])
        if rec is not None:
            nid = msg.get("node") or state.get("node_id", LOCAL_NODE)
            if nid == rec.node_id or nid in rec.copies:
                self._free_shm_name(msg["shm_name"], nid)
            else:
                rec.copies[nid] = msg["shm_name"]
            self.stats["objects_transferred"] += 1
        reply()

    def _addr_for(self, rec: WorkerRec, remote: bool) -> str:
        """The address a client should dial for this worker: remote (Ray-
        Client-analogue) drivers can only reach TCP listeners."""
        return rec.addr_tcp if remote and rec.addr_tcp else rec.addr

    def _pull_addr_for(self, node_id: str) -> Optional[str]:
        """Where to pull a node's objects from: the head itself serves n0's
        namespace; agents serve theirs; remote-client namespaces have no
        server (their puts are uploaded to n0, so nothing lives there that
        another node would pull)."""
        if node_id == LOCAL_NODE:
            return self.tcp_addr
        node = self.nodes.get(node_id)
        # draining nodes keep serving pulls: drain evacuation and borrowers
        # both read from them until the deadline
        return node.addr if node is not None and node.up else None

    def _locate_fields(self, rec: ObjectRec, caller_node: str) -> dict:
        # every live holder, so a puller can split the byte range across
        # copies (windowed multi-source pulls).  The primary leads; the
        # legacy single-source fields stay for mixed-version pullers.
        # The caller's own copy is never offered as a pull source — if it
        # were readable the caller would not be asking.
        sources = []
        primary_addr = self._pull_addr_for(rec.node_id)
        if primary_addr is not None:
            name = rec.shm_name or (
                f"spill:{rec.spill_path}" if rec.spill_path else None
            )
            if name:
                sources.append(
                    {"node": rec.node_id, "shm_name": name,
                     "pull_addr": primary_addr}
                )
        for nid, name in rec.copies.items():
            addr = self._pull_addr_for(nid)
            if addr is not None and nid != caller_node:
                sources.append(
                    {"node": nid, "shm_name": name, "pull_addr": addr}
                )
        if rec.node_id != caller_node and caller_node in rec.copies:
            # prefer the caller's local copy — but KEEP the sources list, so
            # a stale local copy (evicted under the directory's feet) still
            # fails over to the live remote holders instead of erroring
            return {
                "found": True, "shm_name": rec.copies[caller_node],
                "size": rec.size, "owner": rec.owner, "node": caller_node,
                "pull_addr": None, "sources": sources,
            }
        return {
            "found": True, "shm_name": rec.shm_name, "size": rec.size,
            "owner": rec.owner, "node": rec.node_id,
            "pull_addr": primary_addr,
            "spill_path": rec.spill_path,
            "sources": sources,
        }

    async def _h_obj_locate(self, state, msg, reply, reply_err):
        rec = self.objects.get(msg["oid"])
        if rec is None:
            reply(found=False)
            return
        # prefer a copy on the caller's node
        reply(**self._locate_fields(rec, state.get("node_id", LOCAL_NODE)))

    def _routable_tcp(self, addr_tcp: str, node_id: str) -> str:
        """Worker/driver TCP listeners bind loopback or wildcard; a dial
        from ANOTHER host needs the node's reachable address.  Substitute
        the host this head (or the node's agent) registered for that node —
        the one component that knows the cluster topology."""
        if not addr_tcp:
            return addr_tcp
        proto, _, rest = addr_tcp.partition(":")
        host, _, port = rest.rpartition(":")
        if host not in ("127.0.0.1", "0.0.0.0", "localhost", "::", "::1"):
            return addr_tcp
        if node_id == LOCAL_NODE:
            reach = self.tcp_addr
        else:
            node = self.nodes.get(node_id)
            reach = node.addr if node is not None else None
        if not reach:
            return addr_tcp
        reach_host = reach.partition(":")[2].rpartition(":")[0]
        return f"{proto}:{reach_host}:{port}" if reach_host else addr_tcp

    async def _h_client_addr(self, state, msg, reply, reply_err):
        """p2p directory lookup: where does client_id serve RPCs?  One call
        per OWNER (cached by the consumer), after which location resolution
        for every object that owner creates goes worker-to-worker
        (owner_locate) — the ownership-based object directory's read path
        (ownership_based_object_directory.h role).  The head remains the
        arbiter for pins/spill/GC and the fallback when an owner dies."""
        cid = msg["client_id"]
        info = self.client_addrs.get(cid)
        if info is None:
            rec = self.workers.get(cid)
            if rec is None or rec.state == "dead":
                dead = (
                    (rec is not None and rec.state == "dead")
                    or cid in self._departed_clients
                )
                reply(found=False, dead=dead)
                return
            info = {
                "addr": rec.addr or "",
                "addr_tcp": rec.addr_tcp or "",
                "node": rec.node_id,
            }
        addr_tcp = self._routable_tcp(info.get("addr_tcp") or "", info["node"])
        if state.get("remote"):
            # TCP-only callers can't dial unix sockets
            if not addr_tcp:
                reply(found=False)
                return
            reply(found=True, addr=addr_tcp, node=info["node"])
            return
        reply(
            found=True,
            addr=info.get("addr") or addr_tcp,
            addr_tcp=addr_tcp,
            node=info["node"],
        )

    async def _h_obj_spilled(self, state, msg, reply, reply_err):
        """Producer moved an object's bytes to disk under memory pressure
        (local_object_manager.h spill).  The old shm slice is reclaimed
        immediately when nothing holds a zero-copy view of it; otherwise the
        reclaim waits for the last pin to drop."""
        self.stats["objects_spilled_bytes"] = (
            self.stats.get("objects_spilled_bytes", 0) + int(msg.get("size") or 0)
        )
        if msg.get("decided"):
            # ownership plane: the OWNER already made the free-now-vs-defer
            # call against its ledger's pin state; this notify just keeps
            # the registry snapshot (locate/pull routing, failover) current
            rec = self.objects.get(msg["oid"])
            if rec is not None:
                for nid, name in rec.copies.items():
                    self._free_shm_name(name, nid)
                rec.copies.clear()
                rec.spill_path = msg["path"]
                rec.shm_name = None
                rec.pending_free = None
                self.stats["objects_spilled"] = (
                    self.stats.get("objects_spilled", 0) + 1
                )
            reply(found=rec is not None, free_now=False)
            return
        rec = self.objects.get(msg["oid"])
        if rec is None:
            reply(found=False, free_now=False)
            return
        old = rec.shm_name
        rec.spill_path = msg["path"]
        rec.shm_name = None
        # secondary copies are droppable outright — free them on their nodes
        # before forgetting them, or their arena slices leak
        for nid, name in rec.copies.items():
            self._free_shm_name(name, nid)
        rec.copies.clear()
        pinned = any(h.endswith("#v") for h in rec.holders)
        if not pinned:
            # the holder truth is owner-resident: a reader's #v pin on this
            # object lives in the OWNER's ledger (owner_pin), not here —
            # consult the last synced digest before freeing a slice a view
            # may be mapping.  The residual window is one owner_sync period
            # (plus the owner's own pins, which the digest excludes by
            # design); deferral via pending_free is the safe direction —
            # worst case the slice is reclaimed at object settle instead.
            info = self.owner_digests.get(rec.owner, {}).get(rec.oid)
            if info is not None:
                pinned = any(
                    h.endswith("#v") for h in info.get("b") or ()
                )
        if old is None:
            reply(found=True, free_now=False)
        elif pinned:
            rec.pending_free = old
            reply(found=True, free_now=False)
        else:
            # the producer frees its slice synchronously (it needs the space
            # now); no reclaim broadcast needed
            reply(found=True, free_now=True)
        self.stats["objects_spilled"] = self.stats.get("objects_spilled", 0) + 1

    async def _h_obj_pin(self, state, msg, reply, reply_err):
        """Confirmed zero-copy pin: registering the pin and learning the
        object's CURRENT location is one atomic head-side step, so a reader
        can never map a slice that spilling is about to recycle."""
        rec = self.objects.get(msg["oid"])
        if rec is None:
            reply(found=False)
            return
        if not self._forward_to_owner(
            rec.owner,
            {"m": "owner_refs", "inc": [msg["oid"]], "as_id": msg["as_id"]},
        ):
            rec.holders.add(msg["as_id"])
        # else: pin fallback for an owner-resident object (owner_pin dial
        # failed) — the pin must land in the owner's ledger or its
        # spill_transition would free the slice under the reader.  The
        # location replied below is the registry's view; the owner's notify
        # keeps it current, so the residual race window is one in-flight
        # obj_spilled, same as the pre-plane path.
        reply(**self._locate_fields(rec, state.get("node_id", LOCAL_NODE)))

    async def _h_pull_chunk(self, state, msg, reply, reply_err):
        """Serve a chunk of one of n0's objects for node-to-node transfer
        (object_manager.h chunked push analogue; the head doubles as n0's
        object server since n0 has no agent)."""
        delay = getattr(self.config, "testing_transfer_delay_s", 0.0)
        if delay:
            # test/bench hook: simulated link latency, so the windowed-pull
            # A/B measures pipelining rather than loopback memcpy speed
            await asyncio.sleep(delay)
        reply(data=read_shm_chunk(
            self.session_name, self._pull_maps, msg["shm_name"], msg["off"], msg["len"]
        ))

    async def _h_obj_refs(self, state, msg, reply, reply_err):
        # as_id: synthetic holder ids ("<cid>#v" value pins keep an arena
        # slice alive while zero-copy views of it outlive the ObjectRef)
        cid = msg.get("as_id") or state.get("client_id", "?")
        if cid in self._spent_transit:
            # the receiver already acked this transit: the pin is moot
            del self._spent_transit[cid]
        else:
            inc = msg.get("inc", [])
            if inc and msg.get("ttl") and cid.startswith("t:"):
                # track for the TTL sweep (lost-reply reclamation).  Only
                # pins that opt in (bounded-ack protocols like owner_locate
                # serving); task-arg pins ack at execution time, which lease
                # queueing can delay past any fixed TTL — those are cleaned
                # by sender liveness (the disconnect sweep) instead
                self._transit_pins[cid] = (time.monotonic(), list(inc))
            for oid in inc:
                rec = self.objects.get(oid)
                if rec is not None:
                    if cid != rec.owner and self._forward_to_owner(
                        rec.owner,
                        {
                            "m": "owner_refs", "inc": [oid], "as_id": cid,
                            "ttl": bool(msg.get("ttl")),
                        },
                    ):
                        # a borrower's registration that fell back here while
                        # the owner (the lifetime authority) is alive: land
                        # it in the owner's ledger, not as head-side residue
                        # an owner settle would silently clobber
                        continue
                    rec.holders.add(cid)
                else:
                    # inc may race ahead of obj_created (different sockets)
                    self._early_ref_add(oid, cid)
        for oid in msg.get("dec", []):
            rec = self.objects.get(oid)
            if rec is not None:
                if (
                    cid not in rec.holders
                    and cid != rec.owner
                    and self._forward_to_owner(
                        rec.owner,
                        {"m": "owner_refs", "dec": [oid], "as_id": cid},
                    )
                ):
                    # release fallback for a hold that lives in the (alive)
                    # owner's ledger — e.g. the direct dial failed once at
                    # release time; without the forward the hold would pin
                    # the object until the borrower process dies
                    continue
                rec.holders.discard(cid)
                if cid == rec.owner:
                    rec.owner_released = True
                if (
                    rec.pending_free
                    and cid.endswith("#v")
                    and not any(h.endswith("#v") for h in rec.holders)
                ):
                    # last zero-copy pin on a spilled object's old slice gone
                    self._free_shm_name(rec.pending_free, rec.node_id)
                    rec.pending_free = None
                self._obj_maybe_gc(rec)
            else:
                early = self._early_refs.get(oid)
                if early is not None:
                    early.discard(cid)
                    if not early:
                        del self._early_refs[oid]
                        self._early_ref_ts.pop(oid, None)

    # placement groups ------------------------------------------------------
    @staticmethod
    def _pg_demand(bundles: List[BundleRec]) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for b in bundles:
            for k, v in b.resources.items():
                total[k] = total.get(k, 0.0) + v
        return total

    def _pg_infeasible(self, bundles: List[BundleRec], strategy: str) -> Optional[str]:
        """A PG is infeasible only if it can never fit the current cluster's
        TOTAL capacity (strategy-aware); temporary shortage means pending."""
        alive = self._alive_nodes()
        if strategy == "STRICT_PACK":
            demand = self._pg_demand(bundles)
            if not any(self._fits(n.total, demand) for n in alive):
                return f"STRICT_PACK: no node's total capacity fits {demand}"
            return None
        if strategy == "STRICT_SPREAD" and len(bundles) > len(alive):
            return f"STRICT_SPREAD: {len(bundles)} bundles > {len(alive)} nodes"
        for b in bundles:
            cands = [
                n for n in alive
                if b.labels is None or scheduling.match_labels(n.labels, b.labels)
            ]
            if not cands:
                return f"bundle label selector {b.labels} matches no alive node"
            if not any(self._fits(n.total, b.resources) for n in cands):
                return f"bundle {b.resources} fits no eligible node's total capacity"
        demand = self._pg_demand(bundles)
        if not self._fits(self._agg_total(), demand):
            return f"need {demand}, cluster total {self._agg_total()}"
        return None

    def _try_place_pg(self, rec: PGRec) -> bool:
        """Assign nodes to all unplaced bundles (taking node resources).
        Returns True when the whole PG is placed."""
        unplaced = [i for i, b in enumerate(rec.bundles) if b.node_id is None]
        if not unplaced:
            rec.state = "created"
            return True
        nodes = self._alive_nodes()
        if rec.strategy == "STRICT_SPREAD":
            placed_on = {b.node_id for b in rec.bundles if b.node_id is not None}
            nodes = [n for n in nodes if n.node_id not in placed_on]
        views = self._node_views(nodes)
        assignment = scheduling.place_bundles(
            views,
            [rec.bundles[i].resources for i in unplaced],
            rec.strategy,
            self.config.scheduler_spread_threshold,
            bundle_labels=[rec.bundles[i].labels for i in unplaced],
        )
        if assignment is None:
            return False
        for i, nid in zip(unplaced, assignment):
            rec.bundles[i].node_id = nid
            self._take(self.nodes[nid].avail, rec.bundles[i].resources)
        rec.state = "created"
        return True

    async def _h_create_pg(self, state, msg, reply, reply_err):
        """PG semantics mirror GcsPlacementGroupManager: infeasible only if
        the demand exceeds the cluster's TOTAL capacity (strategy-aware); a PG
        that fits total but not currently-free resources is PENDING and is
        created FIFO as leases/actors/PGs release resources (pg_wait blocks
        on it).  Bundles are placed onto nodes per PACK/SPREAD/STRICT_*."""
        blabels = msg.get("bundle_labels") or [None] * len(msg["bundles"])
        bundles = [
            BundleRec(resources=b, labels=l)
            for b, l in zip(msg["bundles"], blabels)
        ]
        strategy = msg.get("strategy", "PACK")
        why = self._pg_infeasible(bundles, strategy)
        if why is not None:
            reply_err(PlacementGroupError(f"infeasible placement group: {why}"))
            return
        rec = PGRec(pg_id=msg["pg_id"], bundles=bundles, strategy=strategy)
        if self._try_place_pg(rec):
            self._log_event("pg_created", pg_id=rec.pg_id, bundles=len(bundles))
        else:
            rec.state = "pending"
            self.pending_pgs.append(rec.pg_id)
            self._log_event("pg_pending", pg_id=rec.pg_id, bundles=len(bundles))
        self.pgs[rec.pg_id] = rec
        reply(state=rec.state)

    def _service_pending_pgs(self):
        """Create pending PGs FIFO as resources free up (no overtaking: a
        large PG at the head of the queue is not starved by later small ones)."""
        while self.pending_pgs:
            pgid = self.pending_pgs[0]
            rec = self.pgs.get(pgid)
            if rec is None or rec.state != "pending":
                self.pending_pgs.popleft()
                continue
            if not self._try_place_pg(rec):
                break
            self.pending_pgs.popleft()
            self._log_event("pg_created", pg_id=pgid, bundles=len(rec.bundles))
            self._wake_pg_waiters(pgid)

    def _wake_pg_waiters(self, pgid: str, exc: Optional[BaseException] = None):
        for fut in self._pg_waiters.pop(pgid, []):
            if not fut.done():
                if exc is None:
                    fut.set_result(True)
                else:
                    fut.set_exception(exc)

    async def _h_pg_wait(self, state, msg, reply, reply_err):
        """Block until the PG is created (or removed / timeout)."""
        pgid = msg["pg_id"]
        rec = self.pgs.get(pgid)
        if rec is None:
            reply_err(PlacementGroupError(f"placement group {pgid} not found"))
            return
        if rec.state == "created":
            reply(ready=True)
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pg_waiters.setdefault(pgid, []).append(fut)
        try:
            # field is named wait_timeout because Connection.call() consumes
            # a kwarg named `timeout` as the RPC deadline instead of sending it
            await asyncio.wait_for(fut, msg.get("wait_timeout"))
            reply(ready=True)
        except asyncio.TimeoutError:
            reply(ready=False)
        except PlacementGroupError as e:
            reply_err(e)

    async def _h_remove_pg(self, state, msg, reply, reply_err):
        pg = self.pgs.pop(msg["pg_id"], None)
        if pg is not None:
            for b in pg.bundles:
                if b.node_id is not None:
                    node = self.nodes.get(b.node_id)
                    if node is not None and node.up:
                        self._give(node.avail, b.resources)
            if pg.state != "created":
                try:
                    self.pending_pgs.remove(msg["pg_id"])
                except ValueError:
                    pass
            self._wake_pg_waiters(
                msg["pg_id"],
                PlacementGroupError(f"placement group {msg['pg_id']} removed"),
            )
            self._service_queue()
        reply()

    async def _h_list_pgs(self, state, msg, reply, reply_err):
        reply(
            pgs=[
                {
                    "pg_id": p.pg_id,
                    "strategy": p.strategy,
                    "state": p.state,
                    "bundles": [b.resources for b in p.bundles],
                    "bundle_nodes": [b.node_id for b in p.bundles],
                }
                for p in self.pgs.values()
            ]
        )

    # introspection ---------------------------------------------------------
    def _node_lease_blocks(self, n: NodeRec) -> Dict[str, dict]:
        """Merged delegated/used view of one node's lease blocks: size is the
        head's authoritative delegation count, used/counters come from the
        agent's latest heartbeat."""
        out: Dict[str, dict] = {}
        for pool, wids in n.delegated.items():
            if not wids and pool not in n.lease_used:
                continue
            hb = n.lease_used.get(pool) or {}
            out[pool] = {
                "size": len(wids),
                "used": int(hb.get("used", 0)),
                "granted": int(hb.get("granted", 0)),
                "denied": int(hb.get("denied", 0)),
            }
        return out

    async def _h_lease_dir(self, state, msg, reply, reply_err):
        """Submitter-side lease directory: which agents hold delegated lease
        blocks, at what occupancy.  Read once per pool per TTL while a pool
        grows (cached client-side) — NOT per lease and never per task, so
        steady-state floods put zero load here."""
        nodes = []
        for n in self._alive_nodes():
            if n.is_local or n.conn is None:
                continue
            # only pools with live slots: a fully-revoked block (size 0)
            # would make every submitter probe the agent, get denied, and
            # eagerly re-fetch this directory — MORE head traffic than the
            # central path, the opposite of the plane's purpose
            blocks = {
                p: b
                for p, b in self._node_lease_blocks(n).items()
                if b["size"] > 0
            }
            if blocks:
                nodes.append({"node_id": n.node_id, "addr": n.addr, "pools": blocks})
        reply(nodes=nodes, delegation=self.config.lease_delegation)

    async def _h_nodes(self, state, msg, reply, reply_err):
        from .nodeagent import node_load_sample

        out = []
        for n in self.nodes.values():
            out.append(
                {
                    "node_id": n.node_id,
                    "alive": n.up,  # draining nodes are up (but unschedulable)
                    "state": n.state,
                    # fencing token: bumps every time this node id rejoins
                    # after a death verdict (partition heals prove freshness)
                    "incarnation": n.incarnation,
                    "drain": (
                        {
                            "reason": n.drain_reason,
                            "deadline_in_s": round(
                                max(0.0, n.drain_deadline - time.monotonic()), 3
                            ),
                        }
                        if n.state == "draining"
                        else None
                    ),
                    "resources": n.total,
                    "available": n.avail,
                    "labels": n.labels,
                    "load": n.load if not n.is_local else node_load_sample(),
                    "is_head_node": n.is_local,
                    # agent pid (same-host test tooling: PreemptionSimulator
                    # sends the preemption SIGTERM straight to it)
                    "pid": n.pid,
                    # Prometheus scrape endpoint (node-agent HTTP, head-free)
                    "metrics_addr": n.metrics_addr,
                    "lease_blocks": self._node_lease_blocks(n),
                    "n_workers": sum(
                        1
                        for w in self.workers.values()
                        if w.node_id == n.node_id and w.state != "dead"
                    ),
                }
            )
        reply(nodes=out)

    async def _h_cluster_resources(self, state, msg, reply, reply_err):
        reply(total=self._agg_total(), available=self._agg_avail())

    async def _h_stats(self, state, msg, reply, reply_err):
        from .protocol import wire_stats

        # the head's own frame/message counters prove control-plane
        # amortization end-to-end: rpc_messages_* / rpc_frames_* > 1 means
        # batch envelopes are doing their job (shown by `ca status`)
        wire = {f"rpc_{k}": v for k, v in wire_stats().items()}
        # lease-plane aggregates: delegated slots and the agents' lifetime
        # local-grant counters (heartbeat-fed) vs this head's central grants
        # — `ca status` shows regressions without the dashboard
        lease_local_granted = 0
        lease_local_used = 0
        lease_delegated = 0
        for n in self._alive_nodes():
            for pool, wids in n.delegated.items():
                lease_delegated += len(wids)
            seen_granted = {
                pool: int((hb or {}).get("granted", 0))
                for pool, hb in n.lease_used.items()
            }
            lease_local_granted += sum(seen_granted.values())
            lease_local_used += sum(
                int((hb or {}).get("used", 0)) for hb in n.lease_used.values()
            )
        # log-plane counters: cluster-wide ca_log_* aggregates (capture-side,
        # flushed by every worker) next to this head's own shipped/dropped
        # stats — `ca status` shows both
        log_counters = self._log_counter_totals()
        # drain plane: the client-side evacuated-task counter aggregates
        # through the metrics table (submitters count their exempted retries)
        evac = self.metrics.get("ca_drain_tasks_evacuated_total")
        drain_tasks_evacuated = (
            int(sum(evac["data"].values())) if evac and evac.get("data") else 0
        )
        reply(
            rpc_counts=dict(self.rpc_counts),
            stats=dict(
                self.stats,
                **wire,
                **log_counters,
                lease_delegated_slots=lease_delegated,
                lease_local_used=lease_local_used,
                lease_local_granted=lease_local_granted,
                lease_head_granted=self.stats["leases_granted"],
                drain_tasks_evacuated=drain_tasks_evacuated,
                nodes_draining=sum(
                    1 for n in self.nodes.values() if n.state == "draining"
                ),
                pending_leases=len(self.pending_leases),
                idle_workers=sum(
                    len(d) for n in self._alive_nodes() for d in n.idle.values()
                ),
                n_workers=sum(1 for w in self.workers.values() if w.state != "dead"),
                n_actors=len(self.actors),
                n_objects=len(self.objects),
                n_nodes=len(self._alive_nodes()),
            )
        )

    async def _h_list_actors(self, state, msg, reply, reply_err):
        # limit applied server-side: a 10k-actor table must not cross the
        # wire to honor limit=10.  Explicit limit=0 means zero, not default.
        limit = msg.get("limit")
        limit = 10_000 if limit is None else limit
        reply(
            actors=[
                self._actor_info(a)
                for a in itertools.islice(self.actors.values(), limit)
            ]
        )

    async def _h_list_workers(self, state, msg, reply, reply_err):
        limit = msg.get("limit")
        limit = 10_000 if limit is None else limit
        reply(
            workers=[
                {
                    "worker_id": w.worker_id,
                    "pid": w.pid,
                    "state": w.state,
                    "actor_id": w.actor_id,
                    "node_id": w.node_id,
                }
                for w in itertools.islice(self.workers.values(), limit)
            ]
        )

    async def _h_task_events(self, state, msg, reply, reply_err):
        self.task_events.extend(msg.get("events") or [])

    async def _h_list_task_events(self, state, msg, reply, reply_err):
        events = list(self.task_events)
        if msg.get("terminal"):
            # terminal-executions view: drop lifecycle phases and app spans
            # BEFORE the limit, so limit=N means N executions even when
            # tracing multiplies ring entries per task
            events = [
                e for e in events
                if e.get("end") is not None
                and e.get("state") in ("FINISHED", "FAILED")
            ]
        name = msg.get("name")
        if name:
            events = [e for e in events if e.get("name") == name]
        st = msg.get("state")
        if st:
            events = [e for e in events if e.get("state") == st]
        tid = msg.get("task_id")
        if tid:
            # trace assembly: all lifecycle phases of one task
            events = [e for e in events if e.get("task_id") == tid]
        limit = msg.get("limit") or 10_000
        reply(events=events[-limit:])

    def digest_holders(self, rec) -> tuple:
        """(num_holders, from_ledger) for display surfaces: the holder truth
        is owner-resident, so when the owner has synced a digest surface it
        (borrower set + implied owner hold unless released) — head-side
        holders are empty by design in steady state.  Shared by
        _h_list_objects and the dashboard's /api/objects."""
        info = self.owner_digests.get(rec.owner, {}).get(rec.oid)
        if info is None:
            return len(rec.holders), False
        return len(info.get("b") or ()) + (0 if info.get("r") else 1), True

    async def _h_list_objects(self, state, msg, reply, reply_err):
        limit = msg.get("limit") or 10_000
        out = []
        for rec in list(self.objects.values())[:limit]:
            holders, ledger = self.digest_holders(rec)
            out.append(
                {
                    "object_id": rec.oid.hex(),
                    "size": rec.size,
                    "owner": rec.owner,
                    "in_shm": rec.shm_name is not None,
                    "num_holders": holders,
                    "owner_ledger": ledger,
                    "node_id": rec.node_id,
                }
            )
        reply(objects=out)

    async def _h_metrics_report(self, state, msg, reply, reply_err):
        from ..util.metrics import merge_metric_records

        merge_metric_records(self.metrics, msg.get("metrics"))
        self._ingest_flightrec(msg.get("flightrec"))

    def _flightrec_query(
        self, *, trace=None, plane=None, node=None, event=None,
        since=None, limit=1000,
    ) -> Dict[str, Any]:
        """Filter/sort the cluster-merged flight-recorder journal.  Shared
        by the `flightrec` RPC and the dashboard's /api/flightrec route."""
        events = list(self.flightrec)
        if trace:
            events = [
                e for e in events if (e.get("trace") or {}).get("tid") == trace
            ]
        if plane:
            events = [e for e in events if e.get("plane") == plane]
        if node:
            events = [e for e in events if e.get("node") == node]
        if event:
            events = [e for e in events if event in (e.get("event") or "")]
        if since is not None:
            events = [e for e in events if e.get("ts", 0) >= float(since)]
        events.sort(key=lambda e: e.get("ts", 0))
        limit = int(limit)
        if limit and len(events) > limit:
            events = events[-limit:]
        return {
            "events": events, "total": len(self.flightrec),
            "enabled": self._flightrec_on,
        }

    async def _h_flightrec(self, state, msg, reply, reply_err):
        """Flight-recorder query: the cluster-merged decision journal,
        filtered by trace id / plane / node / event substring / since-ts,
        sorted by timestamp.  Backs `ca events`, `ca incident`,
        `util.state.flightrec_events`, and dashboard /api/flightrec."""
        reply(**self._flightrec_query(
            trace=msg.get("trace"), plane=msg.get("plane"),
            node=msg.get("node"), event=msg.get("event"),
            since=msg.get("since"), limit=msg.get("limit", 1000),
        ))

    async def _h_metrics_snapshot(self, state, msg, reply, reply_err):
        reply(metrics=self.metrics)

    async def _h_timeseries(self, state, msg, reply, reply_err):
        """Metrics-plane history: ring-buffered series at the requested tier
        (0 = scrape resolution, 1 = coarse), optionally counter→rate derived
        server-side.  Backs `/api/timeseries`, `util.state.timeseries()`,
        dashboard sparklines, and `ca top`."""
        if self.timeseries is None:
            reply(series={}, meta={"disabled": True})
            return
        reply(
            series=self.timeseries.query(
                names=msg.get("names"),
                prefix=msg.get("prefix"),
                tier=int(msg.get("tier", 0)),
                rate=bool(msg.get("rate")),
            ),
            meta=self.timeseries.meta(),
        )

    async def _h_profile(self, state, msg, reply, reply_err):
        """`ca profile` routing: resolve a worker / actor / task / node /
        "head" id to the owning process and trigger its in-process stack
        sampler; the folded stacks + speedscope JSON stream back through
        here.  The head samples itself off-loop (the sampler thread reads
        sys._current_frames; the loop keeps dispatching)."""
        ident = msg.get("id") or "head"
        duration = float(msg.get("duration", 2.0))
        hz = float(msg.get("hz", 100.0))
        node = self.nodes.get(ident)
        if ident == "head" or (node is not None and node.is_local):
            # the head node has no separate agent: its node id profiles the
            # head process itself (not a "no such id" error)
            from ..util import profiler

            res = await asyncio.get_running_loop().run_in_executor(
                None, profiler.sample_stacks, duration, hz
            )
            reply(
                target="head", node_id=LOCAL_NODE,
                folded=profiler.render_folded(res["folded"]),
                speedscope=profiler.speedscope_json(res["folded"], "head", hz),
                samples=res["samples"], duration_s=res["duration_s"],
            )
            return
        # node id -> that node's agent process
        if node is not None and not node.is_local:
            if node.conn is None or node.conn.closed:
                reply_err(ConnectionError(f"agent for node {ident!r} unreachable"))
                return
            try:
                out = await node.conn.call(
                    "profile", duration=duration, hz=hz, timeout=duration + 15
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                reply_err(RuntimeError(f"profile of node {ident!r} failed: {e}"))
                return
            reply(target=ident, node_id=ident, **{
                k: out[k] for k in ("folded", "speedscope", "samples", "duration_s")
            })
            return
        wid = ident
        # actor id -> its worker
        for a in self.actors.values():
            if a.actor_id == ident or a.actor_id.startswith(ident):
                wid = a.worker_id
                break
        else:
            # task id -> the worker its most recent lifecycle event ran on
            if ident not in self.workers:
                for ev in reversed(self.task_events):
                    if ev.get("task_id") == ident and ev.get("worker_id"):
                        wid = ev["worker_id"]
                        break
        rec = self.workers.get(wid)
        if rec is None or rec.state == "dead" or not rec.addr:
            reply_err(ValueError(
                f"no live worker/actor/task/node with id {ident!r}"
            ))
            return
        try:
            conn = await self._worker_conn(rec)
            out = await conn.call(
                "profile", duration=duration, hz=hz, timeout=duration + 15
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            reply_err(RuntimeError(f"profile of {wid!r} failed: {e}"))
            return
        reply(target=wid, node_id=rec.node_id, **{
            k: out[k] for k in ("folded", "speedscope", "samples", "duration_s")
        })

    async def _h_autoscaler_state(self, state, msg, reply, reply_err):
        """What the autoscaler reconciler consumes (autoscaler.proto analogue):
        pending demand shapes + current utilization."""
        reply(
            pending_demands=[dict(r.shape) for r in self.pending_leases],
            total=self._agg_total(),
            available=self._agg_avail(),
            idle_workers=sum(
                len(d) for n in self._alive_nodes() for d in n.idle.values()
            ),
            n_workers=sum(1 for w in self.workers.values() if w.state != "dead"),
        )

    async def _h_update_resources(self, state, msg, reply, reply_err):
        """Autoscaler grows/shrinks the local node's capacity as provider
        nodes join/leave (the v1 provider models capacity, not real hosts;
        real hosts join as agent nodes via register)."""
        delta = msg.get("delta") or {}
        node = self.local_node
        for k, v in delta.items():
            node.total[k] = node.total.get(k, 0.0) + v
            node.avail[k] = node.avail.get(k, 0.0) + v
        node.max_workers = int(node.total.get("CPU", 4)) * 4 + 4
        self._log_event("resources_updated", delta=delta, total=node.total)
        self._service_queue()
        reply(total=self._agg_total())

    async def _h_job_stop(self, state, msg, reply, reply_err):
        reply()
        self._shutdown.set()

    # ------------------------------------------------------------ lifecycle
    def _sweep_client_arenas(self, cid: str, node_id: str):
        """Unlink a departed client's arena files (on its node).  Readers with
        live maps keep their data; objects owned by a dead process are lost
        either way (ObjectLostError) until lineage reconstruction recovers
        them."""
        if node_id == LOCAL_NODE:
            import glob

            for path in glob.glob(
                os.path.join("/dev/shm", self.session_name, LOCAL_NODE, f"arena_{cid}_*")
            ):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        else:
            node = self.nodes.get(node_id)
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    node.conn.notify("sweep_arenas", cid=cid)
                except Exception:
                    pass

    async def _on_disconnect(self, state):
        cid = state.get("client_id")
        if cid is None:
            return
        cur = self._clients.get(cid)
        if cur is not None and cur is not state:
            # a NEWER registration under the same id superseded this
            # connection (e.g. a fenced agent's deferred transport close
            # firing after its fresh-incarnation rejoin): tearing down the
            # live registrant over a stale socket would re-kill the node
            # that just healed
            return
        self._clients.pop(cid, None)
        self.client_addrs.pop(cid, None)  # p2p dials now fall back to head
        self._log_subs.pop(cid, None)  # departed drivers stop receiving logs
        if cid in self._repl_subs:
            # a departed standby must not gate sync commits
            self._repl_drop_sub(cid, "disconnect")
        if state.get("role") == "agent":
            node = self.nodes.get(state.get("node_id"))
            if node is not None:
                await self._on_node_death(node)
            return
        self._sweep_client_arenas(cid, state.get("node_id", LOCAL_NODE))
        # abort any client uploads cut off mid-stream: close the mmaps and
        # unlink the partial cput files, or crashed-client retries accumulate
        # leaked multi-GB segments until teardown
        for name, m, _size in state.pop("cput", {}).values():
            try:
                m.close()
            except Exception:
                pass
            try:
                os.unlink(os.path.join("/dev/shm", name))
            except OSError:
                pass
        # drop this client's pubsub channel and its holder entries (incl. the
        # "<cid>#v" value pins) so departed readers can't pin objects forever
        self.subscribers.pop(f"shm_free:{cid}", None)
        writer = state.get("writer")
        if writer is not None:
            # departed drivers leave the broadcast channels (`actors`), or
            # the lists grow a dead writer per driver lifetime
            for subs in self.subscribers.values():
                if writer in subs:
                    subs.remove(writer)
        pin_id = f"{cid}#v"
        transit_prefix = f"t:{cid}:"
        # cnt:<cid>: containment edges die with the client too — its
        # containers can never release them (OwnerLedger.purge_holder does
        # the same for owner-resident records; adopted records live here)
        cnt_prefix = f"cnt:{cid}:"
        for rec in list(self.objects.values()):
            stale = [
                h
                for h in rec.holders
                if h == cid
                or h == pin_id
                or h.startswith(transit_prefix)
                or h.startswith(cnt_prefix)
            ]
            if stale:
                rec.holders.difference_update(stale)
                self._obj_maybe_gc(rec)
        for tok in [t for t in self._transit_pins if t.startswith(transit_prefix)]:
            del self._transit_pins[tok]
        # ownership plane: every OTHER owner's ledger must purge this
        # client's holder ids/pins/tokens/containment edges too — they can
        # never dec (broadcast, like the drain pub: no subscription
        # round-trip may gate lifetime correctness)
        gone_frame = {"m": "pub", "ch": "client_gone", "data": {"client_id": cid}}
        for st in list(self._clients.values()):
            try:
                write_frame(st["writer"], gone_frame)
            except Exception:
                pass
        # ... and this OWNER's orphaned objects are adopted from its last
        # owner_sync digest: the borrowers recorded there drain through the
        # central path; the owner itself is dead, so its release is implied
        digest = self.owner_digests.pop(cid, None)
        if digest:
            adopted = 0
            for oid, info in digest.items():
                rec = self.objects.get(oid)
                if rec is None:
                    continue
                rec.holders |= set(info.get("b") or ())
                rec.owner_released = True
                adopted += 1
                self._obj_maybe_gc(rec)
            if adopted:
                self.stats["owners_adopted"] = (
                    self.stats.get("owners_adopted", 0) + 1
                )
                self._log_event(
                    "owner_ledger_adopted", client_id=cid, objects=adopted
                )
        self._departed_clients[cid] = None
        while len(self._departed_clients) > 10_000:
            self._departed_clients.popitem(last=False)
        if state.get("role") == "worker":
            rec = self.workers.get(cid)
            if rec is not None:
                await self._on_worker_death(rec)
        elif state.get("role") == "driver":
            self._driver_clients.discard(cid)
            if not self._driver_clients and os.environ.get("CA_HEAD_PERSIST") != "1":
                # last driver gone -> tear down the job (detached actors would
                # survive in the multi-job milestone)
                self._shutdown.set()

    async def _loop_lag_loop(self):
        """Measure this loop's own scheduling lag: sleep a fixed period and
        observe the overshoot.  Lag is THE head-saturation signal — every
        handler that blocks the loop (big snapshot, O(n) scan, dispatch
        flood) shows up here before it shows up as client timeouts.  Gauge =
        latest sample (`ca_head_loop_lag_seconds`); histogram accumulates
        the distribution for p50/p99 in bench/`ca top`."""
        period = max(float(getattr(self.config, "loop_lag_period_s", 0.25)), 0.01)
        loop = asyncio.get_running_loop()
        while not self._shutdown.is_set():
            t0 = loop.time()
            await asyncio.sleep(period)
            lag = max(loop.time() - t0 - period, 0.0)
            self._self_gauge_set(
                "ca_head_loop_lag_seconds",
                "head asyncio event-loop scheduling lag (latest sample)",
                lag,
            )
            self._self_hist_observe(
                "ca_head_loop_lag_hist_seconds",
                "head asyncio event-loop scheduling lag distribution",
                self._DISPATCH_BOUNDS, lag, "[]",
            )

    def _timeseries_tick(self, wall: float) -> None:
        """One retention sample: head stats (cumulative counters), computed
        cluster gauges (incl. the drain/owner-plane aggregates, so the PR
        5/6 surfaces get history, not just current values), and the whole
        aggregated metrics table (counters, gauges, histogram _count/_sum)."""
        store = self.timeseries
        for k, v in self.stats.items():
            if isinstance(v, (int, float)):
                store.record(f"head_{k}", "[]", float(v), "counter", wall)
        gauges = {
            "nodes_draining": sum(
                1 for n in self.nodes.values() if n.state == "draining"
            ),
            "n_nodes": sum(1 for n in self.nodes.values() if n.up),
            "n_workers": sum(1 for w in self.workers.values() if w.state != "dead"),
            "n_actors": len(self.actors),
            "n_objects": len(self.objects),
            "pending_leases": len(self.pending_leases),
            "idle_workers": sum(
                len(d) for n in self._alive_nodes() for d in n.idle.values()
            ),
            "owner_digest_entries": sum(
                len(d) for d in self.owner_digests.values()
            ),
        }
        for k, v in gauges.items():
            store.record(f"head_{k}", "[]", float(v), "gauge", wall)
        from .protocol import wire_stats

        for k, v in wire_stats().items():
            store.record(f"head_rpc_{k}", "[]", float(v), "counter", wall)
        store.sample_metrics(self.metrics, wall)

    async def _monitor_loop(self):
        period = self.config.health_check_period_s
        from ..util import flightrec as _flightrec

        while not self._shutdown.is_set():
            await asyncio.sleep(min(period, 0.2))
            now = time.monotonic()
            if self._flightrec_on and _flightrec.REC is not None:
                # head-process recorder (netchaos and other shared code
                # running here) drains straight into the merged ring — the
                # head is its own aggregator, no piggyback needed
                self._ingest_flightrec(_flightrec.REC.drain())
            if (
                self.timeseries is not None
                and now - self._last_ts_sample
                >= float(getattr(self.config, "timeseries_interval_s", 10.0))
            ):
                self._last_ts_sample = now
                try:
                    self._timeseries_tick(time.time())
                except Exception:
                    pass  # retention must never take down the monitor
            # HA observability: the epoch gauge is always live; replication
            # lag (records the slowest standby hasn't acked) gauges + a
            # throttled flight-recorder event while standbys are subscribed
            self._self_gauge_set(
                "ca_head_ha_epoch", "current head authority epoch",
                float(self.head_epoch),
            )
            if self._repl_subs:
                lag = self._repl_seq - min(
                    s["acked"] for s in self._repl_subs.values()
                )
                self._self_gauge_set(
                    "ca_head_ha_repl_lag",
                    "replication records not yet acked by the slowest standby",
                    float(lag),
                )
                if now - self._repl_last_lag_event > 10.0:
                    self._repl_last_lag_event = now
                    self._log_event(
                        "ha_replicate_lag", lag=lag, seq=self._repl_seq,
                        standbys=len(self._repl_subs),
                    )
            for rec in list(self.workers.values()):
                if rec.state == "dead":
                    continue
                if rec.proc is not None and rec.proc.poll() is not None:
                    await self._on_worker_death(rec)
                    continue
                if rec.proc is None and rec.node_id == LOCAL_NODE and rec.pid:
                    # re-adopted after a head restart: no Popen handle, poll
                    # the pid directly
                    try:
                        os.kill(rec.pid, 0)
                    except ProcessLookupError:
                        await self._on_worker_death(rec)
                        continue
                    except PermissionError:
                        pass
                if (
                    rec.state != "starting"
                    and now - rec.last_heartbeat
                    > period * self.config.health_check_failure_threshold
                ):
                    await self._on_worker_death(rec)
            for node in list(self.nodes.values()):
                if not node.up or node.is_local:
                    continue
                if (
                    now - node.last_heartbeat
                    > period * self.config.health_check_failure_threshold
                ):
                    await self._on_node_death(node)
                    continue
                if node.state == "draining" and (
                    now >= node.drain_deadline or self._drain_quiesced(node)
                ):
                    await self._drain_finalize(node)
            if self._spent_transit:
                # expire tombstones whose late pin never arrived (sender died)
                cutoff = now - 60.0
                for tok in [t for t, ts in self._spent_transit.items() if ts < cutoff]:
                    del self._spent_transit[tok]
            if self._transit_pins:
                # reclaim pins whose transit_done was lost (receiver's RPC
                # timed out after the sender pinned).  10 minutes is far
                # beyond any live transfer, so this can only fire on a
                # genuinely lost ack
                cutoff = now - 600.0
                for tok in [
                    t for t, (ts, _) in self._transit_pins.items() if ts < cutoff
                ]:
                    _, oids = self._transit_pins.pop(tok)
                    for oid in oids:
                        rec = self.objects.get(oid)
                        if rec is not None and tok in rec.holders:
                            rec.holders.discard(tok)
                            self._obj_maybe_gc(rec)
                        early = self._early_refs.get(oid)
                        if early is not None:
                            early.discard(tok)
            if self._early_refs:
                # explicit, bounded grace for refs that arrived before their
                # obj_created: entries older than the window can only belong
                # to producers that died before registering — sweep them so
                # they can't pin future records or grow without bound
                cutoff = now - getattr(self.config, "early_ref_grace_s", 600.0)
                expired = [
                    o for o, ts in self._early_ref_ts.items() if ts < cutoff
                ]
                for o in expired:
                    self._early_ref_ts.pop(o, None)
                    self._early_refs.pop(o, None)
                if expired:
                    self.stats["early_refs_expired"] = (
                        self.stats.get("early_refs_expired", 0) + len(expired)
                    )
                    self._log_event("early_refs_expired", count=len(expired))
            if (
                self.mem_monitor is not None
                and now - self._last_mem_check
                >= self.config.memory_monitor_refresh_ms / 1000.0
            ):
                self._last_mem_check = now
                self._memory_pressure_check()
            if now - self._last_dir_touch > 30.0:
                # liveness marker: concurrent inits skip sweeping session
                # dirs with a recent mtime, protecting idle clusters and the
                # head-restart window from _sweep_stale_sessions
                self._last_dir_touch = now
                try:
                    os.utime(self.session_dir)
                except OSError:
                    pass

    def _memory_pressure_check(self):
        """Kill at most one worker per pressured node per refresh period
        (worker_killing_policy.h).  The retry/restart machinery turns the
        SIGKILL into a task retry or actor restart downstream."""
        from . import memory_monitor as mm

        for node in self.nodes.values():
            if node.state != "alive":
                continue
            if node.is_local:
                if not self.mem_monitor.is_pressured():
                    continue
            elif node.mem_pressured:
                node.mem_pressured = False  # re-armed by the next heartbeat
            else:
                continue
            cands = []
            for rec in self.workers.values():
                if rec.node_id != node.node_id or rec.state not in (
                    "idle",
                    "leased",
                    "actor",
                    # block workers are valid victims too: on an agent node
                    # in steady state EVERY pool worker is delegated, and
                    # excluding them would leave memory pressure with no
                    # candidate at all.  The head can't see whether a local
                    # lease is running on one, so it is treated like a
                    # leased worker (retriable: the submitter's retry budget
                    # absorbs the kill; the agent reaps and shrinks the
                    # block).
                    "delegated",
                ):
                    continue
                a = self.actors.get(rec.actor_id) if rec.actor_id else None
                cands.append(mm.Candidate(
                    worker=rec,
                    is_idle=rec.state == "idle",
                    retriable=rec.state in ("leased", "delegated")
                    or (a is not None and a.can_restart),
                    busy_since=rec.busy_since,
                ))
            victim = mm.pick_victim(cands)
            if victim is None:
                continue
            self.stats["oom_kills"] += 1
            self._log_event(
                "worker_oom_killed",
                worker_id=victim.worker_id,
                node_id=node.node_id,
                state=victim.state,
            )
            self._kill_worker_rec(victim)

    async def run(self):
        if not self._ha_sock_deferred:
            try:
                os.unlink(self.sock_path)  # stale socket from a killed head
            except FileNotFoundError:
                pass
        await self.server.start()
        # advertise the TCP endpoint for agents / cross-host clients
        for a in self.server.bound_addrs:
            if a.startswith("tcp:"):
                self.tcp_addr = a
        if self.ha_role == "standby":
            await self._run_standby()
            return
        if self._restored and await self._ha_boot_probe():
            # a successor head owns this session: stay demoted (refusing
            # everything) until the demote-exit grace fires.  head.addr is
            # left alone — it names the real head.
            await self._shutdown.wait()
            await self._teardown()
            return
        if self._ha_sock_deferred:
            # the probe found no live authority behind head.addr: this head
            # IS the cluster again — claim the session socket like a
            # promotion does
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass
            self._sock_server = Server(
                [self.sock_path], self._handle, self._on_disconnect
            )
            await self._sock_server.start()
            self._ha_sock_deferred = False
        with open(os.path.join(self.session_dir, "head.addr"), "w") as f:
            f.write(self.tcp_addr or "")
        # prestart one worker per CPU (worker_pool.h prestart behavior);
        # a restarted head re-adopts its surviving workers instead
        if self.config.worker_prestart and not self._restored:
            for _ in range(int(self.local_node.total.get("CPU", 1))):
                self._spawn_worker()
        if self._restored:
            self._log_event(
                "head_restarted",
                workers=len(self.workers),
                actors=len(self.actors),
                nodes=len(self.nodes),
            )
            # resume drains interrupted by the restart: re-announce to the
            # re-registering clients and re-run the evacuation pass (idempotent
            # — already-migrated actors/objects are no longer on the node)
            for node in self.nodes.values():
                if node.state == "draining":
                    self._pub_drain(node)
                    spawn_bg(self._drain_evacuate(node))
        # HTTP dashboard (dashboard/head.py analogue): zero extra process,
        # the head answers from its own tables
        self.dashboard = None
        try:
            from ..dashboard import Dashboard

            self.dashboard = Dashboard(self)
            await self.dashboard.start(
                getattr(self.config, "head_host", "127.0.0.1"),
                int(os.environ.get("CA_DASHBOARD_PORT", "0")),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._log_event("dashboard_failed", error=repr(e))
        # named + exception-logged: a dead monitor/persist loop is a head
        # that stops detecting node death or persisting state — it must
        # warn the moment it dies, not at GC time
        self._ha_start_active_loops()
        # readiness marker for the driver — atomic rename: a reader must
        # never observe the file existing but empty (the pid parse treats
        # that as a dead cluster and refuses to connect)
        ready_path = os.path.join(self.session_dir, "head.ready")
        with open(ready_path + ".tmp", "w") as f:
            f.write(str(os.getpid()))
        os.replace(ready_path + ".tmp", ready_path)
        await self._shutdown.wait()
        for t in self._ha_tasks:
            t.cancel()
        if self.dashboard is not None:
            await self.dashboard.stop()
        await self._teardown()

    async def _run_standby(self):
        """Warm-standby service loop: advertise the rank-suffixed discovery
        files, run the subscribe/apply FSM, and — on promotion — continue
        as the active head (the standby loop already started the active
        loops and claimed the session files)."""
        from ..util.aio import spawn_logged

        addr_file = os.path.join(
            self.session_dir, f"head.standby{self.ha_rank}.addr"
        )
        with open(addr_file + ".tmp", "w") as f:
            f.write(self.tcp_addr or "")
        os.replace(addr_file + ".tmp", addr_file)
        standby_task = spawn_logged(
            self._ha_standby_loop(), f"head-standby{self.ha_rank}"
        )
        ready = os.path.join(
            self.session_dir, f"head.standby{self.ha_rank}.ready"
        )
        with open(ready + ".tmp", "w") as f:
            f.write(str(os.getpid()))
        os.replace(ready + ".tmp", ready)
        await self._shutdown.wait()
        standby_task.cancel()
        for t in self._ha_tasks:
            t.cancel()
        if self._ha_replog is not None:
            self._ha_replog.close()
        await self._teardown()

    async def _teardown(self):
        if self.ha_role != "active":
            # a never-promoted standby or a fenced zombie owns NOTHING of
            # the session (workers, shm namespace, discovery files all
            # belong to the active head): just release the sockets
            if self._sock_server is not None:
                await self._sock_server.stop()
            await self.server.stop()
            return
        for node in self.nodes.values():
            if node.conn is not None and not node.conn.closed:
                try:
                    node.conn.notify("node_shutdown")
                    from .protocol import flush_writer

                    flush_writer(node.conn.writer)
                except Exception:
                    pass
        for rec in self.workers.values():
            if rec.state == "dead":
                continue
            if rec.proc is not None and rec.proc.poll() is None or (
                rec.proc is None and rec.node_id == LOCAL_NODE and rec.pid
            ):
                try:
                    os.kill(rec.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        if self._sock_server is not None:
            await self._sock_server.stop()
        await self.server.stop()
        # GC all shm segments of this session (local host; agents clean their
        # own namespaces on shutdown)
        import shutil

        shutil.rmtree(os.path.join("/dev/shm", self.session_name), ignore_errors=True)


def read_shm_chunk(session_name: str, map_cache: Dict[str, Any], shm_name: str, off: int, length: int) -> bytes:
    """Read one chunk of a local object for node-to-node transfer.  Shared by
    the head (serving n0) and node agents (serving their node).  Serves shm
    arena slices (seal-sequence verified), dedicated segments, and spilled
    disk files ("spill:<path>").  Names/paths are validated against the
    session namespace (no path escapes)."""
    import mmap as _mmap

    from .errors import StaleObjectError
    from .object_store import _SLICE_HDR, ShmObjectStore

    if shm_name.startswith("spill:"):
        path = shm_name[len("spill:"):]
        if f"/{session_name}/" not in path or ".." in path or "/spill/" not in path:
            raise ValueError(f"invalid spill path {path!r}")
        fd = os.open(path, os.O_RDONLY)
        try:
            m = _mmap.mmap(fd, os.fstat(fd).st_size, prot=_mmap.PROT_READ)
            return bytes(memoryview(m)[off : off + length])
        finally:
            os.close(fd)
    if not shm_name.startswith(session_name + "/") or ".." in shm_name:
        raise ValueError(f"invalid shm name {shm_name!r}")
    file_name = shm_name.split("@", 1)[0]
    base = 0
    seq = 0
    if "@" in shm_name:
        _, base, _size, seq = ShmObjectStore.parse_slice(shm_name)
    m = map_cache.get(file_name)
    if m is None:
        fd = os.open(os.path.join("/dev/shm", file_name), os.O_RDONLY)
        try:
            m = _mmap.mmap(fd, os.fstat(fd).st_size, prot=_mmap.PROT_READ)
        finally:
            os.close(fd)
        map_cache[file_name] = m
    if seq:
        cur = int.from_bytes(bytes(m[base : base + _SLICE_HDR]), "little")
        if cur != seq:
            raise StaleObjectError(f"slice {shm_name} recycled while serving")
        base += _SLICE_HDR
    return bytes(memoryview(m)[base + off : base + off + length])


def drop_pull_map(map_cache: Dict[str, Any], shm_name: str) -> None:
    """Invalidate the serving-side map of an unlinked shm file, so transfer
    caches don't pin pages of deleted objects (arena files are owned by their
    producer and are never dropped here)."""
    file_name = shm_name.split("@", 1)[0]
    if "@" in shm_name:
        return  # arena slice: the arena file outlives the object
    m = map_cache.pop(file_name, None)
    if m is not None:
        try:
            m.close()
        except (BufferError, ValueError):
            pass


def main():
    session_dir = os.environ["CA_SESSION_DIR"]
    config = CAConfig.from_json(os.environ["CA_CONFIG_JSON"])
    import json

    resources = json.loads(os.environ.get("CA_RESOURCES", '{"CPU": 4}'))
    head = Head(session_dir, config, resources)

    def _loop_factory():
        loop = asyncio.new_event_loop()
        if hasattr(asyncio, "eager_task_factory"):
            loop.set_task_factory(asyncio.eager_task_factory)
        return loop

    if hasattr(asyncio, "Runner"):  # 3.11+
        with asyncio.Runner(loop_factory=_loop_factory) as runner:
            runner.run(head.run())
    else:
        loop = _loop_factory()
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(head.run())
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            finally:
                asyncio.set_event_loop(None)
                loop.close()


if __name__ == "__main__":
    main()
