"""Node memory monitor + worker-killing policy.

Reference parity: ``src/ray/common/memory_monitor.h:52`` (periodic usage
sampling against a kill threshold) and
``src/ray/raylet/worker_killing_policy.h`` (pick which worker dies when the
node is about to OOM).  The policy here mirrors the reference's retriable-
first / LIFO preference: killing the newest retriable work loses the least
progress and the runtime's existing retry machinery transparently re-runs it.

The monitor itself is process-agnostic: the head runs one over its local
node's workers and every node agent runs one over its own (the kill is
always taken by the process that owns the worker's pid).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Sequence, Tuple

# Sampling sources, in preference order (first readable wins):
#   1. CA_TEST_MEM_USAGE_PATH — a test-injected file "used_bytes total_bytes"
#   2. cgroup v2  (/sys/fs/cgroup/memory.current + memory.max)
#   3. cgroup v1  (memory.usage_in_bytes + memory.limit_in_bytes)
#   4. /proc/meminfo (MemTotal - MemAvailable)
_CG2 = "/sys/fs/cgroup"
_CG1 = "/sys/fs/cgroup/memory"
# limits above this are "no limit" sentinels (cgroup v1 reports PAGE_COUNTER_MAX)
_NO_LIMIT = 1 << 60


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            txt = f.read().strip()
        if txt == "max":
            return None
        return int(txt)
    except (OSError, ValueError):
        return None


def _meminfo() -> Optional[Tuple[int, int]]:
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                fields[k] = int(rest.split()[0]) * 1024
        total = fields["MemTotal"]
        avail = fields.get("MemAvailable", fields.get("MemFree", 0))
        return total - avail, total
    except (OSError, KeyError, ValueError, IndexError):
        return None


class MemoryMonitor:
    """Samples node memory usage and answers "are we about to OOM?".

    ``threshold`` is the used/total fraction above which the killing policy
    engages (memory_monitor.h's usage_threshold, default 0.95).
    """

    def __init__(self, threshold: float = 0.95):
        self.threshold = threshold

    def sample(self) -> Optional[Tuple[int, int]]:
        """(used_bytes, total_bytes), or None if nothing is readable."""
        test_path = os.environ.get("CA_TEST_MEM_USAGE_PATH")
        if test_path:
            try:
                with open(test_path) as f:
                    used, total = f.read().split()
                return int(used), int(total)
            except (OSError, ValueError):
                return None  # test hook present but unreadable: no verdict
        cur = _read_int(os.path.join(_CG2, "memory.current"))
        lim = _read_int(os.path.join(_CG2, "memory.max"))
        if cur is not None and lim is not None and lim < _NO_LIMIT:
            return cur, lim
        cur = _read_int(os.path.join(_CG1, "memory.usage_in_bytes"))
        lim = _read_int(os.path.join(_CG1, "memory.limit_in_bytes"))
        if cur is not None and lim is not None and lim < _NO_LIMIT:
            return cur, lim
        return _meminfo()

    def is_pressured(self) -> bool:
        s = self.sample()
        if s is None:
            return False
        used, total = s
        return total > 0 and used / total > self.threshold


class Candidate(NamedTuple):
    """One worker the killing policy may choose.

    ``retriable`` means killing it only costs a transparent re-run — an
    actor with restarts left, or a leased task worker (leases carry no
    per-task retry budget, so the policy assumes the configured default
    budget > 0; a max_retries=0 task on a leased worker is the accepted
    imprecision of that assumption).  ``busy_since`` is the monotonic time
    the current work started (0 if unknown).
    """

    worker: object
    is_idle: bool
    retriable: bool
    busy_since: float


def pick_victim(cands: Sequence[Candidate]) -> Optional[object]:
    """Choose the worker to kill under memory pressure.

    Order of preference (worker_killing_policy.h group policy, condensed):
      1. idle workers — free memory without losing any work at all;
      2. retriable busy workers, newest work first (LIFO: least progress lost);
      3. non-retriable busy workers, newest first (last resort — the caller
         sees a crash, but the node survives).
    Returns the chosen ``Candidate.worker``, or None if ``cands`` is empty.
    """
    idle = [c for c in cands if c.is_idle]
    if idle:
        # newest-started idle worker: the prestarted pool keeps its elders
        return max(idle, key=lambda c: c.busy_since).worker
    retriable = [c for c in cands if c.retriable]
    pool = retriable or list(cands)
    if not pool:
        return None
    return max(pool, key=lambda c: c.busy_since).worker
