"""Runtime context (analogue of python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Optional

from .worker import global_worker


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.job_id

    @property
    def node_id(self):
        return self._worker.node_id

    def get_task_id(self) -> Optional[str]:
        t = self._worker.current_task_id
        return t.hex() if t else None

    def get_actor_id(self) -> Optional[str]:
        a = self._worker.current_actor_id
        return a.hex() if a else None

    def get_worker_id(self) -> str:
        return self._worker.client_id

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False  # filled in by the actor-restart milestone


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker())
