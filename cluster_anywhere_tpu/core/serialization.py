"""Value serialization.

Uses cloudpickle with pickle protocol 5 out-of-band buffers so that numpy
arrays (and any buffer-exporting object) are serialized without copies: the
pickle stream holds only metadata while raw buffers are collected separately
and written directly into shared memory.  This mirrors the reference's
zero-copy plasma reads (python/ray/_private/serialization.py) in spirit, with
the TPU-native twist that `jax.Array` device values are never serialized at
all — they become DeviceRef handles resolved in the owning process (see
object_ref.DeviceRef).

Wire format of a serialized value (used both inline and in shm):
    meta: msgpack {pickle: bytes, buffer_lens: [int, ...]}
    followed by the concatenated raw buffers (8-byte aligned each).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle
import msgpack

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Returns (pickle_bytes, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []
    data = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    return data, buffers

def deserialize(data: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(data, buffers=buffers)


def pack(value: Any) -> bytes:
    """Serialize into a single contiguous blob (inline path)."""
    data, buffers = serialize(value)
    raws = [b.raw() for b in buffers]
    header = msgpack.packb(
        {"p": data, "l": [len(r) for r in raws]}, use_bin_type=True
    )
    parts = [len(header).to_bytes(4, "big"), header]
    offset = 4 + len(header)
    for r in raws:
        pad = _align(offset) - offset
        parts.append(b"\x00" * pad)
        parts.append(bytes(r))
        offset += pad + len(r)
    return b"".join(parts)


def unpack(blob) -> Any:
    """Inverse of pack(). Accepts bytes or a memoryview (zero-copy for
    buffer-backed payloads when given a memoryview over shm)."""
    mv = memoryview(blob)
    hlen = int.from_bytes(bytes(mv[:4]), "big")
    header = msgpack.unpackb(bytes(mv[4 : 4 + hlen]), raw=False)
    offset = 4 + hlen
    buffers = []
    for ln in header["l"]:
        offset = _align(offset)
        buffers.append(mv[offset : offset + ln])
        offset += ln
    return deserialize(header["p"], buffers)


def packed_size(data: bytes, raws: List[Any]) -> int:
    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    offset = 4 + len(header)
    for r in raws:
        offset = _align(offset) + len(r)
    return offset


def pack_into(buf: memoryview, data: bytes, raws: List[Any]) -> int:
    """Write the pack() format into a preallocated buffer (e.g. shm mapping).
    Returns bytes written."""
    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    hlen = len(header)
    buf[:4] = hlen.to_bytes(4, "big")
    buf[4 : 4 + hlen] = header
    offset = 4 + hlen
    for r in raws:
        offset = _align(offset)
        ln = len(r)
        buf[offset : offset + ln] = r if isinstance(r, (bytes, memoryview)) else memoryview(r)
        offset += ln
    return offset
