"""Value serialization.

Uses cloudpickle with pickle protocol 5 out-of-band buffers so that numpy
arrays (and any buffer-exporting object) are serialized without copies: the
pickle stream holds only metadata while raw buffers are collected separately
and written directly into shared memory.  This mirrors the reference's
zero-copy plasma reads (python/ray/_private/serialization.py) in spirit, with
the TPU-native twist that `jax.Array` device values are never serialized at
all — they become DeviceRef handles resolved in the owning process (see
object_ref.DeviceRef).

Wire format of a serialized value (used both inline and in shm):
    meta: msgpack {pickle: bytes, buffer_lens: [int, ...]}
    followed by the concatenated raw buffers (8-byte aligned each).
"""

from __future__ import annotations

import contextlib
import pickle
import threading
from typing import Any, List, Optional, Tuple

import cloudpickle
import msgpack

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


# --- nested-ObjectRef capture (borrowed-reference protocol) ---------------
# ObjectRef.__reduce__ appends the ref's id to the active capture list while
# a value is being pickled, so senders know which references a serialized
# value smuggles across the process boundary (reference_count.h borrowing:
# the sender pins them until the receiver registers its own).
_capture_tls = threading.local()


@contextlib.contextmanager
def ref_capture():
    """Collect ids (bytes) of ObjectRefs pickled within the block."""
    prev = getattr(_capture_tls, "refs", None)
    _capture_tls.refs = []
    try:
        yield _capture_tls.refs
    finally:
        _capture_tls.refs = prev


def note_serialized_ref(id_bytes: bytes) -> None:
    refs: Optional[list] = getattr(_capture_tls, "refs", None)
    if refs is not None:
        refs.append(id_bytes)


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Returns (pickle_bytes, out_of_band_buffers).

    Plain pickle first (5-10x faster); cloudpickle only for values plain
    pickle can't handle (lambdas, local classes) — mirroring the reference's
    split between inline serialization and cloudpickled definitions."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        data = pickle.dumps(value, protocol=5, buffer_callback=buffers.append)
        # plain pickle serializes __main__-defined classes/functions BY
        # REFERENCE, which a worker process (different __main__) cannot
        # resolve; cloudpickle serializes them by value.  The module name is
        # embedded in the stream, so scan for it (false positives only cost
        # the slower path).
        if b"__main__" in data:
            raise pickle.PicklingError("references __main__")
    except (pickle.PicklingError, AttributeError, TypeError):
        buffers.clear()
        data = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    return data, buffers

def deserialize(data: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(data, buffers=buffers)


_PACKED_NONE: bytes | None = None


def pack(value: Any) -> bytes:
    """Serialize into a single contiguous blob (inline path)."""
    global _PACKED_NONE
    if value is None:
        if _PACKED_NONE is None:
            data, _ = serialize(None)
            header = msgpack.packb({"p": data, "l": []}, use_bin_type=True)
            _PACKED_NONE = len(header).to_bytes(4, "big") + header
        return _PACKED_NONE
    data, buffers = serialize(value)
    raws = [b.raw() for b in buffers]
    header = msgpack.packb(
        {"p": data, "l": [len(r) for r in raws]}, use_bin_type=True
    )
    parts = [len(header).to_bytes(4, "big"), header]
    offset = 4 + len(header)
    for r in raws:
        pad = _align(offset) - offset
        parts.append(b"\x00" * pad)
        parts.append(bytes(r))
        offset += pad + len(r)
    return b"".join(parts)


def pack_chunks(value: Any):
    """Like pack(), but returns (total_len, chunks) without assembling one
    contiguous blob — a scatter-write sink (e.g. a shm channel) copies each
    chunk straight into place, saving a full extra copy of every large
    tensor/array buffer.  Chunk layout is byte-identical to pack()."""
    data, buffers = serialize(value)
    return pack_chunks_from_parts(data, [b.raw() for b in buffers])


def pack_chunks_from_parts(data: bytes, raws: List[Any]):
    """pack_chunks for already-serialized (pickle_bytes, raw_buffers)."""
    header = msgpack.packb(
        {"p": data, "l": [len(r) for r in raws]}, use_bin_type=True
    )
    chunks: List[Any] = [len(header).to_bytes(4, "big"), header]
    offset = 4 + len(header)
    for r in raws:
        pad = _align(offset) - offset
        if pad:
            chunks.append(b"\x00" * pad)
        chunks.append(r)
        offset += pad + len(r)
    return offset, chunks


def unpack(blob, pin_cb=None) -> Any:
    """Inverse of pack(). Accepts bytes or a memoryview (zero-copy for
    buffer-backed payloads when given a memoryview over shm).

    pin_cb: called once after ALL zero-copy buffers handed to the value have
    been garbage-collected.  Buffers are wrapped in weakref-able ndarray
    shims so the store can keep the backing slice alive exactly as long as
    any user-held view (arena slices get reused; without the pin, a view
    outliving its ObjectRef would silently read recycled bytes)."""
    mv = memoryview(blob)
    hlen = int.from_bytes(bytes(mv[:4]), "big")
    header = msgpack.unpackb(bytes(mv[4 : 4 + hlen]), raw=False)
    offset = 4 + hlen
    buffers = []
    for ln in header["l"]:
        offset = _align(offset)
        buffers.append(mv[offset : offset + ln])
        offset += ln
    if pin_cb is not None and buffers:
        import weakref

        import numpy as _np

        wrapped = [_np.frombuffer(b, dtype=_np.uint8) for b in buffers]
        remaining = {"n": len(wrapped)}

        def _one_done():
            remaining["n"] -= 1
            if remaining["n"] == 0:
                pin_cb()

        for w in wrapped:
            weakref.finalize(w, _one_done)
        return deserialize(header["p"], wrapped)
    if pin_cb is not None:
        pin_cb()  # no out-of-band buffers: nothing can alias the slice
    return deserialize(header["p"], buffers)


def packed_size(data: bytes, raws: List[Any]) -> int:
    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    offset = 4 + len(header)
    for r in raws:
        offset = _align(offset) + len(r)
    return offset


def pack_into(buf: memoryview, data: bytes, raws: List[Any]) -> int:
    """Write the pack() format into a preallocated buffer (e.g. shm mapping).
    Returns bytes written."""
    header = msgpack.packb({"p": data, "l": [len(r) for r in raws]}, use_bin_type=True)
    hlen = len(header)
    buf[:4] = hlen.to_bytes(4, "big")
    buf[4 : 4 + hlen] = header
    offset = 4 + hlen
    for r in raws:
        offset = _align(offset)
        ln = len(r)
        buf[offset : offset + ln] = r if isinstance(r, (bytes, memoryview)) else memoryview(r)
        offset += ln
    return offset
