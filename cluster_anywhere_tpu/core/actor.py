"""Actor API: @remote on classes (analogue of python/ray/actor.py).

ActorClass.remote() registers + places the actor via the head; ActorHandle
holds the actor id and submits method calls directly to the hosting worker.
Handles are serializable and can be passed to tasks/other actors.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from .ids import ActorID
from .object_ref import ObjectRef
from .remote_function import _normalize_pg
from .worker import global_worker

_VALID_ACTOR_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "resources",
    "name",
    "lifetime",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "concurrency_groups",
    "placement_group",
    "placement_group_bundle_index",
    "scheduling_strategy",
    "runtime_env",
    # False = exempt from automatic drain migration (supervisor-managed
    # lifecycles, e.g. serve replicas: the controller drains them app-aware)
    "drain_migration",
}


def method(**options):
    """Per-method options decorator (analogue of ray.method): currently
    num_returns and concurrency_group (see `concurrency_groups` actor
    option; reference concurrency_group_manager.h)."""
    allowed = {"num_returns", "concurrency_group"}
    unknown = set(options) - allowed
    if unknown:
        raise ValueError(f"unknown method option(s): {sorted(unknown)}")

    def wrap(fn):
        fn.__ca_method_options__ = options
        return fn

    return wrap


def _collect_method_options(cls) -> Dict[str, dict]:
    """Gather @method(**opts) annotations from an actor class (method name ->
    options dict); drives caller-side num_returns and creation-time
    concurrency-group validation."""
    out: Dict[str, dict] = {}
    for name in dir(cls):
        if name.startswith("__"):
            continue
        fn = getattr(cls, name, None)
        opts = getattr(fn, "__ca_method_options__", None)
        if opts:
            out[name] = dict(opts)
    return out


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        return self._handle._submit(
            self._method_name, args, kwargs, {"num_returns": self._num_returns}
        )

    def bind(self, *args, **kwargs):
        """Create a DAG node from this actor method (reference:
        dag/class_node.py; enables compiled graphs)."""
        from ..dag.node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def options(self, num_returns: Optional[int] = None, **_ignored) -> "ActorMethod":
        n = self._num_returns if num_returns is None else num_returns
        return ActorMethod(self._handle, self._method_name, n)


class ActorHandle:
    def __init__(
        self,
        actor_id: ActorID,
        max_task_retries: int = 0,
        method_options: Optional[Dict[str, dict]] = None,
    ):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        self._method_options = method_options or {}

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _submit(self, method: str, args, kwargs, opts: Dict[str, Any]):
        w = global_worker()
        merged = {"max_task_retries": self._max_task_retries, **opts}
        if merged.get("num_returns") == "streaming":
            return w.submit_streaming_actor_task(self._actor_id, method, args, kwargs, merged)
        refs = w.submit_actor_task(self._actor_id, method, args, kwargs, merged)
        return refs[0] if merged.get("num_returns", 1) == 1 else refs

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        n = self._method_options.get(name, {}).get("num_returns", 1)
        return ActorMethod(self, name, num_returns=n)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._max_task_retries, self._method_options),
        )


class ActorClass:
    def __init__(self, cls, default_options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._default_options = default_options or {}
        unknown = set(self._default_options) - _VALID_ACTOR_OPTIONS
        if unknown:
            raise ValueError(f"unknown actor option(s): {sorted(unknown)}")
        nt = self._default_options.get("num_tpus")
        if nt:
            from .accelerators import validate_chip_request

            validate_chip_request(float(nt))
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def options(self, **opts) -> "ActorClass":
        unknown = set(opts) - _VALID_ACTOR_OPTIONS
        if unknown:
            raise ValueError(f"unknown actor option(s): {sorted(unknown)}")
        return ActorClass(self._cls, {**self._default_options, **opts})

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        w = global_worker()
        method_options = _collect_method_options(self._cls)
        declared = set(opts.get("concurrency_groups") or {})
        referenced = {
            o["concurrency_group"]
            for o in method_options.values()
            if o.get("concurrency_group") is not None
        }
        undeclared = referenced - declared
        if undeclared:
            raise ValueError(
                f"concurrency group(s) {sorted(undeclared)} used by @method but "
                f"not declared in the actor's concurrency_groups option "
                f"(declared: {sorted(declared)})"
            )
        wire_opts = dict(_normalize_pg(opts))
        wire_opts["method_options"] = method_options or None
        actor_id, _addr = w.create_actor(self._cls, args, kwargs, wire_opts)
        return ActorHandle(
            actor_id,
            max_task_retries=opts.get("max_task_retries", 0),
            method_options=method_options,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__!r} cannot be instantiated directly; "
            f"use .remote()"
        )

    @property
    def underlying(self):
        return self._cls


def get_actor(name: str) -> ActorHandle:
    """Look up a named actor (python/ray/_private/worker.py get_actor)."""
    w = global_worker()
    info = w.get_actor_info(name=name)
    return ActorHandle(
        ActorID.from_hex(info["actor_id"]),
        method_options=info.get("method_options"),
    )


def kill(actor: ActorHandle, no_restart: bool = True):
    global_worker().kill_actor(actor._actor_id, no_restart)


def exit_actor():
    """Terminate the current actor from inside one of its methods."""
    raise SystemExit(0)
