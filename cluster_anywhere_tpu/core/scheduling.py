"""Node scheduling policies (analogue of src/ray/raylet/scheduling/policy/*).

Pure functions over node views so they are unit-testable without a head:
the head passes a list of (node_id, total, avail) snapshots and gets back a
node choice (or a full bundle->node assignment for placement groups).

Policies mirrored from the reference:
- hybrid (hybrid_scheduling_policy.h): pack onto already-used nodes while
  their critical-resource utilization stays below a threshold (default 0.5),
  then spread by least utilization.
- spread (spread_scheduling_policy.h): least-utilized first.
- node affinity (node_affinity_scheduling_policy.h): a specific node, with a
  soft fallback to hybrid.
- bundle placement (bundle_scheduling_policy.h): PACK / SPREAD /
  STRICT_PACK / STRICT_SPREAD over placement-group bundles.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

Shape = Dict[str, float]


def fits(avail: Shape, shape: Shape) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in shape.items())


def take(avail: Shape, shape: Shape) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


def utilization(total: Shape, avail: Shape) -> float:
    """Critical-resource utilization: max over resources of used/total."""
    worst = 0.0
    for k, t in total.items():
        if t > 0:
            u = (t - avail.get(k, 0.0)) / t
            if u > worst:
                worst = u
    return worst


class NodeView:
    """Mutable scheduling snapshot of one node (the policy `take`s from it
    while simulating multi-item placement)."""

    __slots__ = ("node_id", "total", "avail", "index")

    def __init__(self, node_id: str, total: Shape, avail: Shape, index: int = 0):
        self.node_id = node_id
        self.total = dict(total)
        self.avail = dict(avail)
        self.index = index  # join order; lower = longer-lived (head node first)


def rank_hybrid(nodes: Sequence, threshold: float) -> List:
    """Hybrid order: nodes under the utilization threshold first (in join
    order — pack onto the earliest nodes), then the rest by least utilized.
    Accepts any node-like object with .total/.avail/.index (NodeView
    snapshots or the head's live NodeRecs)."""
    below, above = [], []
    for n in nodes:
        (below if utilization(n.total, n.avail) <= threshold else above).append(n)
    below.sort(key=lambda n: n.index)
    above.sort(key=lambda n: utilization(n.total, n.avail))
    return below + above


def rank_spread(nodes: Sequence) -> List:
    return sorted(nodes, key=lambda n: (utilization(n.total, n.avail), n.index))


def pick_node(
    nodes: Sequence[NodeView],
    shape: Shape,
    strategy: Optional[dict] = None,
    threshold: float = 0.5,
) -> Optional[NodeView]:
    """Choose a node for one resource shape. `strategy` is a wire dict:
    None/{"type":"DEFAULT"} = hybrid; {"type":"SPREAD"};
    {"type":"NODE_AFFINITY","node_id":...,"soft":bool}."""
    kind = (strategy or {}).get("type", "DEFAULT")
    if kind == "NODE_AFFINITY":
        want = strategy.get("node_id")
        for n in nodes:
            if n.node_id == want:
                if fits(n.avail, shape):
                    return n
                break
        if not strategy.get("soft", False):
            return None
        kind = "DEFAULT"
    ranked = rank_spread(nodes) if kind == "SPREAD" else rank_hybrid(nodes, threshold)
    for n in ranked:
        if fits(n.avail, shape):
            return n
    return None


def place_bundles(
    nodes: Sequence[NodeView],
    bundles: Sequence[Shape],
    strategy: str,
    threshold: float = 0.5,
) -> Optional[List[str]]:
    """Assign each bundle a node id per the PG strategy, simulating resource
    consumption as it goes.  Returns the node id per bundle, or None if the
    assignment is not currently possible (caller decides pending/infeasible).
    Mutates the passed NodeViews' avail (callers pass snapshots)."""
    out: List[Optional[str]] = [None] * len(bundles)
    if strategy == "STRICT_PACK":
        for n in rank_hybrid(nodes, threshold):
            sim = dict(n.avail)
            if all(_sim_take(sim, b) for b in bundles):
                for i, b in enumerate(bundles):
                    take(n.avail, b)
                    out[i] = n.node_id
                return out  # all on one node
        return None
    if strategy == "STRICT_SPREAD":
        used: set = set()
        for i, b in enumerate(bundles):
            chosen = None
            for n in rank_spread(nodes):
                if n.node_id in used or not fits(n.avail, b):
                    continue
                chosen = n
                break
            if chosen is None:
                return None
            take(chosen.avail, b)
            used.add(chosen.node_id)
            out[i] = chosen.node_id
        return out
    if strategy == "SPREAD":
        # round-robin over least-utilized nodes, wrapping when there are more
        # bundles than nodes (soft spread)
        for i, b in enumerate(bundles):
            chosen = None
            ranked = rank_spread(nodes)
            # prefer a node not used yet by this PG
            used_ids = set(x for x in out if x is not None)
            for n in ranked:
                if n.node_id not in used_ids and fits(n.avail, b):
                    chosen = n
                    break
            if chosen is None:
                for n in ranked:
                    if fits(n.avail, b):
                        chosen = n
                        break
            if chosen is None:
                return None
            take(chosen.avail, b)
            out[i] = chosen.node_id
        return out
    # PACK (default): fill the hybrid-ranked nodes with as few nodes as we can
    for i, b in enumerate(bundles):
        chosen = None
        for n in rank_hybrid(nodes, threshold):
            if fits(n.avail, b):
                chosen = n
                break
        if chosen is None:
            return None
        take(chosen.avail, b)
        out[i] = chosen.node_id
    return out


def _sim_take(avail: Shape, shape: Shape) -> bool:
    if not fits(avail, shape):
        return False
    take(avail, shape)
    return True
