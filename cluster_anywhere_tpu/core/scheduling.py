"""Node scheduling policies (analogue of src/ray/raylet/scheduling/policy/*).

Pure functions over node views so they are unit-testable without a head:
the head passes a list of (node_id, total, avail) snapshots and gets back a
node choice (or a full bundle->node assignment for placement groups).

Policies mirrored from the reference:
- hybrid (hybrid_scheduling_policy.h): pack onto already-used nodes while
  their critical-resource utilization stays below a threshold (default 0.5),
  then spread by least utilization.
- spread (spread_scheduling_policy.h): least-utilized first.
- node affinity (node_affinity_scheduling_policy.h): a specific node, with a
  soft fallback to hybrid.
- node labels (node_label_scheduling_policy.h): hard/soft key->condition
  selectors over the labels each node registered with.  On TPU pods the
  labels carry generation/topology/slice, so this is the gang-placement
  vocabulary (schedule onto "generation in v5e, worker-id 0", etc).
- bundle placement (bundle_scheduling_policy.h): PACK / SPREAD /
  STRICT_PACK / STRICT_SPREAD over placement-group bundles, each bundle
  optionally constrained to label-matching nodes.
"""

from __future__ import annotations

from typing import Container, Dict, List, Optional, Sequence, Tuple

Shape = Dict[str, float]


def fits(avail: Shape, shape: Shape) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in shape.items())


def take(avail: Shape, shape: Shape) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


def utilization(total: Shape, avail: Shape) -> float:
    """Critical-resource utilization: max over resources of used/total."""
    worst = 0.0
    for k, t in total.items():
        if t > 0:
            u = (t - avail.get(k, 0.0)) / t
            if u > worst:
                worst = u
    return worst


class NodeView:
    """Mutable scheduling snapshot of one node (the policy `take`s from it
    while simulating multi-item placement)."""

    __slots__ = ("node_id", "total", "avail", "index", "labels")

    def __init__(
        self,
        node_id: str,
        total: Shape,
        avail: Shape,
        index: int = 0,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.node_id = node_id
        self.total = dict(total)
        self.avail = dict(avail)
        self.index = index  # join order; lower = longer-lived (head node first)
        self.labels = labels or {}


def rank_hybrid(nodes: Sequence, threshold: float) -> List:
    """Hybrid order: nodes under the utilization threshold first (in join
    order — pack onto the earliest nodes), then the rest by least utilized.
    Accepts any node-like object with .total/.avail/.index (NodeView
    snapshots or the head's live NodeRecs)."""
    below, above = [], []
    for n in nodes:
        (below if utilization(n.total, n.avail) <= threshold else above).append(n)
    below.sort(key=lambda n: n.index)
    above.sort(key=lambda n: utilization(n.total, n.avail))
    return below + above


def rank_spread(nodes: Sequence) -> List:
    return sorted(nodes, key=lambda n: (utilization(n.total, n.avail), n.index))


def match_labels(labels: Dict[str, str], selector: Optional[Dict[str, dict]]) -> bool:
    """Does a node's label map satisfy a selector?  Selector values are wire
    dicts: {"op": "in"|"!in"|"exists"|"!exists", "values": [...]}
    (label_selector semantics of node_label_scheduling_policy.h)."""
    if not selector:
        return True
    for key, cond in selector.items():
        op = cond.get("op", "in")
        present = key in labels
        if op == "exists":
            if not present:
                return False
        elif op == "!exists":
            if present:
                return False
        elif op == "in":
            if not present or labels[key] not in cond.get("values", ()):
                return False
        elif op == "!in":
            if present and labels[key] in cond.get("values", ()):
                return False
        else:
            raise ValueError(f"unknown label-selector op {op!r}")
    return True


def filter_rank_labels(nodes: Sequence, strategy: dict, threshold: float) -> List:
    """NODE_LABEL ranking: drop nodes failing the hard selector, then order
    soft-selector matches first; hybrid rank within each tier (so labels pick
    the candidate set and the usual utilization policy picks within it)."""
    hard = strategy.get("hard")
    soft = strategy.get("soft")
    cands = [n for n in nodes if match_labels(getattr(n, "labels", None) or {}, hard)]
    if not soft:
        return rank_hybrid(cands, threshold)
    pref = [n for n in cands if match_labels(getattr(n, "labels", None) or {}, soft)]
    pref_ids = {id(n) for n in pref}
    rest = [n for n in cands if id(n) not in pref_ids]
    return rank_hybrid(pref, threshold) + rank_hybrid(rest, threshold)


def pick_node(
    nodes: Sequence[NodeView],
    shape: Shape,
    strategy: Optional[dict] = None,
    threshold: float = 0.5,
) -> Optional[NodeView]:
    """Choose a node for one resource shape. `strategy` is a wire dict:
    None/{"type":"DEFAULT"} = hybrid; {"type":"SPREAD"};
    {"type":"NODE_AFFINITY","node_id":...,"soft":bool};
    {"type":"NODE_LABEL","hard":selector,"soft":selector}."""
    kind = (strategy or {}).get("type", "DEFAULT")
    if kind == "NODE_AFFINITY":
        want = strategy.get("node_id")
        for n in nodes:
            if n.node_id == want:
                if fits(n.avail, shape):
                    return n
                break
        if not strategy.get("soft", False):
            return None
        kind = "DEFAULT"
    if kind == "NODE_LABEL":
        ranked = filter_rank_labels(nodes, strategy, threshold)
    elif kind == "SPREAD":
        ranked = rank_spread(nodes)
    else:
        ranked = rank_hybrid(nodes, threshold)
    for n in ranked:
        if fits(n.avail, shape):
            return n
    return None


def place_bundles(
    nodes: Sequence[NodeView],
    bundles: Sequence[Shape],
    strategy: str,
    threshold: float = 0.5,
    bundle_labels: Optional[Sequence[Optional[Dict[str, dict]]]] = None,
) -> Optional[List[str]]:
    """Assign each bundle a node id per the PG strategy, simulating resource
    consumption as it goes.  Returns the node id per bundle, or None if the
    assignment is not currently possible (caller decides pending/infeasible).
    Mutates the passed NodeViews' avail (callers pass snapshots).
    `bundle_labels` optionally gives a hard label selector per bundle; a
    bundle only lands on nodes matching its selector."""

    def ok(n: NodeView, i: int) -> bool:
        if bundle_labels is None or bundle_labels[i] is None:
            return True
        return match_labels(n.labels, bundle_labels[i])

    out: List[Optional[str]] = [None] * len(bundles)
    if strategy == "STRICT_PACK":
        for n in rank_hybrid(nodes, threshold):
            if not all(ok(n, i) for i in range(len(bundles))):
                continue
            sim = dict(n.avail)
            if all(_sim_take(sim, b) for b in bundles):
                for i, b in enumerate(bundles):
                    take(n.avail, b)
                    out[i] = n.node_id
                return out  # all on one node
        return None
    if strategy == "STRICT_SPREAD":
        used: set = set()
        for i, b in enumerate(bundles):
            chosen = None
            for n in rank_spread(nodes):
                if n.node_id in used or not ok(n, i) or not fits(n.avail, b):
                    continue
                chosen = n
                break
            if chosen is None:
                return None
            take(chosen.avail, b)
            used.add(chosen.node_id)
            out[i] = chosen.node_id
        return out
    if strategy == "SPREAD":
        # round-robin over least-utilized nodes, wrapping when there are more
        # bundles than nodes (soft spread)
        for i, b in enumerate(bundles):
            chosen = None
            ranked = [n for n in rank_spread(nodes) if ok(n, i)]
            # prefer a node not used yet by this PG
            used_ids = set(x for x in out if x is not None)
            for n in ranked:
                if n.node_id not in used_ids and fits(n.avail, b):
                    chosen = n
                    break
            if chosen is None:
                for n in ranked:
                    if fits(n.avail, b):
                        chosen = n
                        break
            if chosen is None:
                return None
            take(chosen.avail, b)
            out[i] = chosen.node_id
        return out
    # PACK (default): fill the hybrid-ranked nodes with as few nodes as we can
    for i, b in enumerate(bundles):
        chosen = None
        for n in rank_hybrid(nodes, threshold):
            if ok(n, i) and fits(n.avail, b):
                chosen = n
                break
        if chosen is None:
            return None
        take(chosen.avail, b)
        out[i] = chosen.node_id
    return out


def _sim_take(avail: Shape, shape: Shape) -> bool:
    if not fits(avail, shape):
        return False
    take(avail, shape)
    return True


def rank_delegation(
    entries: Sequence[dict], pool: str, exclude: Optional[Container[str]] = None
) -> List[dict]:
    """Order lease-directory entries for a submitter's node choice on the
    lease plane (the client-side mirror of spread scheduling: the head picks
    WHERE capacity is delegated; the submitter only picks among blocks the
    head already sized).  Most free delegated slots first, so concurrent
    submitters fan out instead of stampeding one agent; entries without the
    pool are dropped, as are nodes in `exclude` (draining nodes — their
    blocks are being recalled, and a grant there would be killed at the
    drain deadline).  Occupancy is heartbeat-stale, so callers must treat
    the order as a hint and probe down the list on denial."""
    def free(e: dict) -> int:
        b = (e.get("pools") or {}).get(pool) or {}
        return int(b.get("size", 0)) - int(b.get("used", 0))

    ranked = [
        e
        for e in entries
        if (e.get("pools") or {}).get(pool)
        and not (exclude and e.get("node_id") in exclude)
    ]
    ranked.sort(key=lambda e: (-free(e), e.get("node_id", "")))
    return ranked
