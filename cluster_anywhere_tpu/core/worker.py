"""CoreWorker runtime: the per-process engine embedded in the driver and in
every worker process (analogue of src/ray/core_worker/core_worker.h).

Owns: the IO thread (asyncio loop), the connection to the head, direct
connections to other workers, the in-process memory store, the shm store
client, reference counting, function export, lease-based task submission with
pipelining (normal_task_submitter.h), actor call submission, and get/put/wait.

Threading model: user code calls the blocking public API from any thread; all
socket IO happens on the IO thread.  ObjectRef readiness is tracked in the
MemoryStore (condition-variable waits) so `get`/`wait` never touch the loop.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import netchaos, serialization
from .config import CAConfig, get_config
from .errors import (
    ActorDiedError,
    CAError,
    FencedError,
    GetTimeoutError,
    ObjectLostError,
    StaleObjectError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .function_manager import FunctionManager
from .ids import ActorID, JobID, ObjectID, TaskID, _Counter
from .object_ref import DeviceRef, ObjectRef
from .object_store import MemoryStore, ShmObjectStore, _Entry
from .protocol import (
    TRACE_FIELD,
    WIRE_STATS,
    AddrRing,
    Connection,
    MsgTemplate,
    addr_list,
    spawn_bg,
)
from .ownership import OWNER_STATS, OwnerLedger
from .reference_counter import ReferenceCounter

_global_worker: Optional["Worker"] = None
_global_lock = threading.Lock()

# set to the util.tracing module by tracing.enable() (None = tracing off).
# Submission hot paths read it with one attribute load + branch, so the
# disabled path adds no per-call allocations (acceptance constraint); a
# direct top-level import would also be circular (util.state imports this
# module at import time).
TRACE_HOOK: Optional[Any] = None

# Lease-plane counters (same plain-int discipline as protocol.WIRE_STATS:
# loop-owned increments, flusher-only reads).  local_* = grants/releases
# served by node agents out of delegated lease blocks; head_* = central
# grants; fallbacks = local attempts that fell through to the head (agent
# exhausted/unreachable).  Shipped as ca_lease_* counters by util/metrics.
LEASE_STATS: Dict[str, int] = {
    "local_grants": 0,
    "local_denied": 0,
    "local_released": 0,
    "head_grants": 0,
    "head_released": 0,
    "fallbacks": 0,
}


def lease_stats() -> Dict[str, int]:
    """Snapshot of this process's lease-plane counters."""
    return dict(LEASE_STATS)


# Drain-plane counters (shipped as ca_drain_* by util/metrics).  A task
# retry caused by a drained/preempted node is a SYSTEM failure: it is
# exempted from the user's max_retries budget and counted here instead.
DRAIN_STATS: Dict[str, int] = {
    "tasks_evacuated_total": 0,  # budget-exempt retries off draining nodes
    "leases_recalled_total": 0,  # idle leases returned early on a drain pub
}


def drain_stats() -> Dict[str, int]:
    """Snapshot of this process's drain-plane counters."""
    return dict(DRAIN_STATS)


# Train-plane counters (shipped as ca_train_* by util/metrics).  The elastic
# training story in numbers: proactive preemption restarts (the controller
# reacted to a drain warning BEFORE the kill), checkpoint-barrier outcomes
# inside the warning window, and attempts that were budget-exempt because
# the death was an announced exit rather than an application failure.
TRAIN_STATS: Dict[str, int] = {
    "preempt_restarts_total": 0,   # drain-triggered proactive group rebuilds
    "preempt_barrier_acked_total": 0,    # barriers where every rank checkpointed
    "preempt_barrier_timeout_total": 0,  # barriers torn down without full acks
    "budget_exempt_attempts_total": 0,   # restarts that did not consume max_failures
    "callback_errors_total": 0,    # run_config callback hooks that raised
    "shutdown_errors_total": 0,    # worker-group teardown errors (kill / PG removal)
}


def train_stats() -> Dict[str, int]:
    """Snapshot of this process's train-plane counters."""
    return dict(TRAIN_STATS)


# Transfer-plane counters (shipped as ca_transfer_* by util/metrics).  The
# bulk-byte data plane: windowed node-to-node object pulls, multi-source
# range splitting, client-mode uploads, and the quantized collective ring's
# wire savings.  window_peak_sum / pulls = average per-transfer peak of
# concurrent pull_chunk RPCs (the structural proof the window is open:
# serial pulls peak at exactly 1).
TRANSFER_STATS: Dict[str, int] = {
    "pulls": 0,                 # node-to-node object transfers completed
    "bytes_pulled": 0,          # object bytes received over pull_chunk
    "chunks_pulled": 0,         # pull_chunk responses applied
    "window_peak_sum": 0,       # sum over pulls of peak in-flight RPCs
    "sources_used": 0,          # holders that served >=1 chunk, summed
    "multi_source_pulls": 0,    # pulls that drew from >1 holder
    "source_failovers": 0,      # sources dropped mid-pull (range re-assigned)
    "pull_retry_rounds": 0,     # re-locate rounds after every source failed
    "bytes_uploaded": 0,        # client-mode put bytes streamed to the head
    "copy_notify_deferred": 0,  # obj_copy notifies queued for re-send
    "quant_bytes_saved": 0,     # f32-equivalent minus wire bytes, quantized ring
    "quant_ops": 0,             # quantized collective ops completed
}


def transfer_stats() -> Dict[str, int]:
    """Snapshot of this process's transfer-plane counters."""
    return dict(TRANSFER_STATS)


def _redial_backoff(attempt: int, rng: Optional[random.Random] = None) -> float:
    """Jittered exponential backoff for head redials: base doubles
    0.25s→4s with attempts, scaled by a uniform [0.5, 1.5) draw so N
    workers reconnecting to a restarted head spread out instead of
    arriving as one synchronized storm."""
    base = min(0.25 * (2 ** max(0, min(attempt - 1, 4))), 4.0)
    return base * (0.5 + (rng or random).random())


def _head_epoch_regressed(known: int, offered: Optional[int]) -> bool:
    """True when a register reply proves the answering head is a superseded
    zombie: it offers an authority epoch strictly below one this process
    already adopted from a successor.  Clients refuse such a head (close,
    rotate to the next ring address) instead of handing it their state."""
    return bool(known) and offered is not None and int(offered) < known


def global_worker() -> "Worker":
    if _global_worker is None:
        raise RuntimeError("not initialized — call init() first")
    return _global_worker


def try_global_worker() -> Optional["Worker"]:
    return _global_worker


def set_global_worker(w: Optional["Worker"]):
    global _global_worker
    with _global_lock:
        _global_worker = w


def _is_device_value(value: Any) -> bool:
    """True if the pytree contains jax.Array leaves on an accelerator (or any
    jax array — device-resident values must not transit pickle)."""
    import sys

    if "jax" not in sys.modules:
        return False
    import jax

    found = False

    def check(x):
        nonlocal found
        if isinstance(x, jax.Array):
            found = True
        return x

    try:
        jax.tree_util.tree_map(check, value)
    except Exception:
        return False
    return found


def _device_spec(value: Any) -> str:
    import jax

    def leaf(x):
        if isinstance(x, jax.Array):
            return f"Array{tuple(x.shape)}:{x.dtype}"
        return type(x).__name__

    try:
        return str(jax.tree_util.tree_map(leaf, value))
    except Exception:
        return "<device value>"


@dataclass
class _Lease:
    lease_id: str
    worker_id: str
    addr: str
    inflight: int = 0
    dead: bool = False
    last_idle: float = field(default_factory=time.monotonic)
    # which plane granted this lease: a node agent's address (local grant
    # out of a delegated lease block) or None for the head.  Releases go
    # back to the granter.
    granter: Optional[str] = None
    # node hosting the leased worker: lets the submitter tell a drain/
    # preemption kill (budget-exempt retry) from an app-level worker crash
    node: Optional[str] = None
    # node incarnation the grant was minted under (agent-granted leases):
    # a post-heal audit proves no outstanding grant predates the verdict
    inc: Optional[int] = None


class LeasePool:
    """Per-resource-shape pool of worker leases with pipelining.

    Mirrors the lease reuse + pipelining of NormalTaskSubmitter: hold up to
    `max_leases` concurrent leases per shape, pipeline up to
    `max_inflight_per_lease` pushes onto each, return leases idle beyond the
    timeout so other processes (nested tasks, actors) can use the CPUs.
    """

    def __init__(
        self,
        worker: "Worker",
        shape_key: tuple,
        shape: Dict[str, float],
        pg: Optional[Tuple[str, int]],
        strategy: Optional[Dict[str, Any]] = None,
    ):
        self.worker = worker
        self.shape = shape
        self.pg = pg
        self.strategy = strategy
        self.inflight_total = 0  # pushed + backlogged + acquiring, all lanes
        self.leases: List[_Lease] = []
        self.waiters: deque = deque()
        # fast lane for argless known-function tasks that found no pushable
        # lease: plain (task_id, fn_id, opts, oids) records drained by
        # release()/new-lease callbacks — no per-task coroutine, no Future
        # (the 4k-noop flood otherwise spawns one asyncio.Task per task)
        self.backlog: deque = deque()
        self._dialing: set = set()  # lease addrs with a connect in flight
        self.requests_outstanding = 0
        cfg = worker.config
        self.max_leases = cfg.max_leases_per_shape
        self.max_inflight = cfg.max_inflight_per_lease
        # contended-cluster fair share: while other clients' lease requests
        # are queued at the head, the head pushes a per-client lease cap;
        # this pool sheds down to it as pipelines drain and stops growing
        # past it.  Expires when the head stops re-nudging (contention over).
        self.contended_cap: Optional[int] = None
        self.contended_until = 0.0
        # every lease block denied us while we already hold capacity: the
        # cluster is saturated for this class — rate-limit further growth
        # attempts so a long flood pipelines instead of re-probing
        # agents/head on every release (the pipelining regime absorbs it)
        self._growth_backoff_until = 0.0

    def _pick(self) -> Optional[_Lease]:
        best = None
        for l in self.leases:
            if not l.dead and l.inflight < self.max_inflight:
                if best is None or l.inflight < best.inflight:
                    best = l
        return best

    async def acquire(self) -> _Lease:
        """Get a lease to push one task onto.

        Preference order balances parallelism against pipelining: (1) an idle
        lease — the task starts immediately; (2) grow the pool, but only up to
        the observed demand (inflight + waiting + this task) so a burst of N
        long tasks gets N parallel leases without flooding the head with
        max_leases speculative requests; (3) once growth is exhausted,
        pipeline onto the least-loaded busy lease (the tiny-task throughput
        path: beyond max_leases concurrent tasks, queueing at workers beats
        per-task lease RPCs)."""
        self.inflight_total += 1
        try:
            while True:
                lease = self._pick()
                if lease is not None and lease.inflight == 0:
                    lease.inflight += 1
                    return lease
                if not self._maybe_grow() and lease is not None and self._pipeline_ok():
                    lease.inflight += 1
                    return lease
                fut = asyncio.get_running_loop().create_future()
                self.waiters.append(fut)
                await fut  # raises if the lease request failed terminally
        except BaseException:
            self.inflight_total -= 1
            raise

    _MAX_OUTSTANDING = 8  # lease requests in flight at the head per pool

    def _should_grow(self) -> bool:
        """Grow towards observed demand, with a cap on in-flight lease
        requests so an ungrantable burst doesn't pile a max_leases-deep queue
        at the head (the head re-scans pending requests every release)."""
        if self.requests_outstanding >= self._MAX_OUTSTANDING:
            return False
        live = sum(1 for l in self.leases if not l.dead)
        if live > 0 and time.monotonic() < self._growth_backoff_until:
            return False  # saturated lease plane: pipeline, don't re-probe
        limit = min(self.max_leases, self.inflight_total)
        cap = self._fair_cap()
        if cap is not None:
            limit = min(limit, cap)
        return live + self.requests_outstanding < limit

    def _pipeline_ok(self) -> bool:
        return self._pipeline_ok_for(self.inflight_total)

    def _pipeline_ok_for(self, demand: int) -> bool:
        """Pushing onto a BUSY lease is right only when the leases we already
        have plus those on the way cannot cover demand (the tiny-task flood
        case).  While expected leases >= demand, waiting for one is right —
        pipelining there would serialize long tasks on one worker while the
        rest of the cluster idles.  SPREAD pools never pipeline before the
        lease cap: queueing depth on a warm node is exactly what the
        strategy exists to avoid, so they keep growing instead."""
        live = sum(1 for l in self.leases if not l.dead)
        expected = live + self.requests_outstanding
        if expected >= demand:
            return False
        if expected >= self.max_leases:
            return True
        if self.strategy is not None and self.strategy.get("type") == "SPREAD":
            return False
        return self.requests_outstanding >= self._MAX_OUTSTANDING

    def _delegatable(self) -> bool:
        """Is this pool's lease class grantable node-locally?  Only the hot
        default class qualifies ({"CPU": 1}, no PG, no strategy): PG bundle
        charging and placement policy stay centralized at the head, and
        remote (client-mode) drivers need the head's TCP address mapping."""
        return (
            self.pg is None
            and self.strategy is None
            and self.shape == {"CPU": 1.0}
            and not self.worker.client_mode
            and self.worker.config.lease_delegation
        )

    def _adopt_lease(self, lease: "_Lease"):
        if lease.node:
            # chaos labeling: map the leased worker's address to its node so
            # connections to it ride the right (src, dst) link policy
            netchaos.register_addr(lease.addr, lease.node)
            netchaos.register_addr(
                self.worker._normalize_peer_addr(lease.addr), lease.node
            )
        self.leases.append(lease)
        self.requests_outstanding -= 1
        self._drain_backlog()
        self._wake(self.max_inflight)

    # head-side ttl on lease-plane escalation probes: a delegatable-class
    # request queued at the head expires after this long and the coroutine
    # re-probes the agents — so overflow requests never pin central state
    # (the head only revokes lease blocks for no-ttl pendings)
    _HEAD_PROBE_TTL_S = 2.0

    async def _request_lease(self):
        # lease plane: try the node agents' delegated blocks first — a grant
        # there is one direct agent RPC, zero head traffic (the raylet-grant
        # split; the head stays the fallback granter)
        delegatable = self._delegatable()
        lease_plane = False  # delegated blocks exist somewhere
        if delegatable:
            lease, lease_plane = await self.worker.local_lease_grant("cpu")
            if lease is not None:
                LEASE_STATS["local_grants"] += 1
                self._adopt_lease(lease)
                return
            if lease_plane:
                # the plane exists but denied us — head fallback is an
                # ESCALATION PROBE, not the primary path
                LEASE_STATS["fallbacks"] += 1
                if any(not l.dead for l in self.leases):
                    # blocks exhausted while we already hold capacity:
                    # saturated.  Back off growth so a long flood pipelines
                    # on what it has instead of re-probing agents + head on
                    # every release.  A pool with NO leases never backs off
                    # — it must reach the head for its first grant.
                    self._growth_backoff_until = time.monotonic() + 0.25
                if self.requests_outstanding > 1:
                    # another of this pool's requests is already subscribed
                    # at the head; a second adds nothing the agents' churn
                    # won't deliver first — abandon this growth attempt
                    self.requests_outstanding -= 1
                    return
        kw = {}
        if self.pg is not None:
            kw = {"pg_id": self.pg[0], "bundle_index": self.pg[1]}
        if self.strategy is not None:
            kw["strategy"] = self.strategy
        if lease_plane:
            # without agents in play this stays a classic held-until-granted
            # request: single-node clusters keep their full pending queue
            # (the autoscaler's demand signal) and growth concurrency
            kw["ttl"] = self._HEAD_PROBE_TTL_S
        attempts = 0
        retry_local = False
        while True:
            if delegatable and retry_local:
                # between head (re)subscriptions — expiry or restart window —
                # probe the agents: the lease plane keeps granting while the
                # control plane is down or saturated
                lease, lease_plane = await self.worker.local_lease_grant("cpu")
                if lease is not None:
                    LEASE_STATS["local_grants"] += 1
                    self._adopt_lease(lease)
                    return
            retry_local = True
            try:
                reply = await self.worker.head.call(
                    "request_lease", shape=self.shape, timeout=None, **kw
                )
            except ConnectionError:
                # head died mid-request (restart window): re-issue once the
                # housekeeping loop has reconnected, instead of failing the
                # queued tasks
                attempts += 1
                if self.worker._stopped or self.worker._head_fenced or attempts > 120:
                    self.requests_outstanding -= 1
                    self._fail_waiters(ConnectionError("cluster head unreachable"))
                    return
                # jittered like every other head redial: a failover must not
                # turn N waiting lease pools into a synchronized retry storm
                await asyncio.sleep(_redial_backoff(attempts))
                continue
            except asyncio.CancelledError:
                raise  # shutdown: don't convert cancellation into waiter errors
            except Exception as e:
                # unrecoverable admission errors (e.g. removed placement
                # group) must surface on the waiting tasks, not spin forever
                self.requests_outstanding -= 1
                self._fail_waiters(e)
                return
            if reply.get("expired"):
                # at-capacity probe came back empty (not an error): re-probe
                # the agents, then re-subscribe — waiting for capacity is
                # legitimate indefinitely, exactly like a pending request
                await asyncio.sleep(0.1)
                continue
            LEASE_STATS["head_grants"] += 1
            self._adopt_lease(
                _Lease(
                    reply["lease_id"], reply["worker_id"], reply["addr"],
                    node=reply.get("node"),
                )
            )
            return

    def _wake(self, n: int = 1):
        while self.waiters and n > 0:
            fut = self.waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                n -= 1

    def _fail_waiters(self, exc: BaseException):
        while self.waiters:
            fut = self.waiters.popleft()
            if not fut.done():
                fut.set_exception(exc)
        while self.backlog:
            task_id, fn_id, opts, oids = self.backlog.popleft()
            self.inflight_total -= 1
            self.worker._store_error(oids, exc)

    def _maybe_grow(self) -> bool:
        """Issue one lease request when admission allows (the single place
        growth bookkeeping lives)."""
        if not self._should_grow():
            return False
        self.requests_outstanding += 1
        spawn_bg(self._request_lease())
        return True

    def enqueue_fast(self, task_id, fn_id, opts, oids) -> None:
        """Queue an argless known-function task for callback-drained push
        (IO thread only).  Counts as demand so growth/pipelining see it."""
        trace = opts.get("_trace")
        if trace is not None and TRACE_HOOK is not None:
            TRACE_HOOK.record_task_event(
                task_id.hex(), None, "task", "QUEUED", trace=trace,
                worker_id=self.worker.client_id, node_id=self.worker.node_id,
            )
        self.inflight_total += 1
        self.backlog.append((task_id, fn_id, opts, oids))
        self._maybe_grow()

    def _drain_backlog(self) -> None:
        """Push backlogged tasks onto leases while the same admission rules
        the submit path uses allow it (idle lease, or pipelining regime).
        A lease whose connection isn't established yet pauses the drain
        behind ONE dial coroutine (never a per-task coroutine); a lease
        whose connection broke is marked dead and the item retries on the
        next pick."""
        while self.backlog:
            lease = self._pick()
            if lease is None or (lease.inflight > 0 and not self._pipeline_ok()):
                self._maybe_grow()
                return
            conn = self.worker._conns.get(
                self.worker._normalize_peer_addr(lease.addr)
            )
            if conn is None or conn.closed:
                self._dial_then_drain(lease)
                return
            item = self.backlog.popleft()
            if item[0].binary() in self.worker._cancelled_tasks:
                self.inflight_total -= 1
                self.worker._store_error(
                    item[3], TaskCancelledError("task was cancelled")
                )
                continue
            if not self.worker._push_fast(self, lease, *item):
                # call_cb raised: _push_fast marked the lease dead; retry the
                # item on whatever _pick finds next round
                self.backlog.appendleft(item)

    def _dial_then_drain(self, lease: _Lease) -> None:
        """The granted lease's worker was never contacted (cold client):
        connect once in the background, then resume draining.  Without this,
        every backlogged item would divert to its own slow-path coroutine —
        exactly the flood the backlog lane exists to avoid.  A failed dial
        gives the lease BACK to the head (the worker may be fine — only this
        client's connect failed; keeping it would leak its capacity, since
        only return_lease or worker death ever releases it head-side)."""
        if lease.addr in self._dialing:
            return
        self._dialing.add(lease.addr)

        async def _dial():
            try:
                await self.worker.conn_to(lease.addr)
            except asyncio.CancelledError:
                raise  # the finally still clears _dialing
            except Exception:
                lease.dead = True
                # granter-aware give-back (head or agent); unreachable
                # granters reclaim via their own worker-death/disconnect paths
                self.worker.return_leases([lease])
            finally:
                self._dialing.discard(lease.addr)
                self._drain_backlog()

        spawn_bg(_dial())

    def release(self, lease: _Lease, dead: bool = False):
        self.inflight_total -= 1
        lease.inflight -= 1
        if dead:
            lease.dead = True
        if lease.inflight == 0:
            lease.last_idle = time.monotonic()
            self._maybe_shed(lease)
        self._drain_backlog()
        self._wake()

    def _fair_cap(self) -> Optional[int]:
        if (
            self.contended_cap is not None
            and time.monotonic() <= self.contended_until
        ):
            return self.contended_cap
        return None

    def _maybe_shed(self, lease: _Lease):
        """A pipelined lease just drained while the cluster is contended:
        give it back if this pool holds more than its fair share, so other
        clients' batches run CONCURRENTLY with ours instead of after it."""
        cap = self._fair_cap()
        if cap is None or lease.dead or lease.inflight:
            return
        live = sum(1 for l in self.leases if not l.dead)
        if live <= cap:
            return
        lease.dead = True
        self.leases = [l for l in self.leases if not l.dead]
        self.worker.return_leases([lease])

    def reap_idle(self, now: float, timeout: float) -> List[_Lease]:
        """Leases to give back to their granter (head or node agent)."""
        out = []
        keep = []
        for l in self.leases:
            if l.dead:
                continue
            if (
                l.inflight == 0
                and now - l.last_idle > timeout
                and not self.waiters
                and not self.backlog
            ):
                l.dead = True
                out.append(l)
            else:
                keep.append(l)
        self.leases = [l for l in self.leases if not l.dead]
        return out

    def reap_node(self, node_id: str) -> List[_Lease]:
        """Give back every IDLE lease hosted on `node_id` (drain recall:
        the node is leaving — new pushes must land on survivors).  Busy
        leases run on until the drain deadline; their deaths retry
        budget-exempt."""
        out = []
        for l in self.leases:
            if not l.dead and l.inflight == 0 and l.node == node_id:
                l.dead = True
                out.append(l)
        if out:
            self.leases = [l for l in self.leases if not l.dead]
        return out

    def reap_contended(self) -> List[_Lease]:
        """Another client's lease request is pending at the head: give back
        every idle lease this pool does not need for its own current demand
        (contended-cluster fairness; the 1s reap_idle horizon is for the
        UNcontended case, where keeping warm leases is pure latency win).
        Idle leases are kept only while live pipelining capacity cannot
        cover in-flight demand — and never beyond the fair-share cap."""
        out = []
        cap = self._fair_cap()
        live = sum(1 for l in self.leases if not l.dead)
        cover = sum(l.inflight for l in self.leases if not l.dead)
        demand = self.inflight_total
        for l in self.leases:
            if l.dead or l.inflight > 0:
                continue
            over_cap = cap is not None and live > cap
            if cover < demand and not over_cap:
                cover += self.max_inflight  # kept: about to absorb backlog
                continue
            l.dead = True
            live -= 1
            out.append(l)
        if out:
            self.leases = [l for l in self.leases if not l.dead]
        return out


class Worker:
    """Per-process core runtime."""

    _OWNER_ADDR_NEG_TTL = 5.0  # seconds a failed owner-address lookup caches
    _REFS_FLUSH_DELAY_S = 0.002  # refcount debounce window (IO-loop timer)

    def __init__(
        self,
        mode: str,
        session_dir: str,
        head_sock: str,
        config: Optional[CAConfig] = None,
        client_id: Optional[str] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        serve_addr: Optional[str] = None,
        serve_addr_tcp: Optional[str] = None,
        client_mode: bool = False,
    ):
        self.mode = mode  # "driver" | "worker"
        self.session_dir = session_dir
        self.session_name = os.path.basename(session_dir)
        # HA plane: head_sock may be a comma-separated ring (active head
        # first, then warm standbys).  Failed dials rotate through it; the
        # standbys list on every register reply merges in, so a client
        # started with one address still learns every promotion candidate.
        self._head_ring = AddrRing(addr_list(head_sock))
        self.head_sock = self._head_ring.current or head_sock
        self.head_epoch = 0  # highest head authority epoch observed
        self.config = config or get_config()
        self.client_id = client_id or f"{mode}-{os.getpid()}-{os.urandom(3).hex()}"
        self.serve_addr = serve_addr
        self.serve_addr_tcp = serve_addr_tcp
        # Ray-Client-analogue remote driver: reaches the cluster over TCP
        # only, claims a private client node id (its /dev/shm is invisible to
        # the cluster), and uploads escaping objects to the head's store
        self.client_mode = client_mode
        self.job_id = JobID.from_random()
        # which node this process runs on (n0 = the head's own node; agent
        # nodes set CA_NODE_ID for their workers)
        if client_mode:
            self.node_id = f"client-{self.client_id}"
        else:
            self.node_id = os.environ.get("CA_NODE_ID", "n0")
        self.memory_store = MemoryStore()
        self.shm_store = ShmObjectStore(
            self.session_name,
            owner_tag=self.client_id,
            node_id=self.node_id,
            budget_bytes=(config or get_config()).object_store_memory,
        )
        self.shm_store.spill_cb = self._spill_bytes
        self.shm_store.spill_kick_cb = self._spill_kick
        self._spill_lock = threading.Lock()  # one spill pass at a time
        self._spill_start_lock = threading.Lock()  # thread creation only
        self._spill_queue: Optional[Any] = None
        self._spill_thread: Optional[threading.Thread] = None
        # inline = a put paid spill latency (hard wall); background = the
        # watermark spiller ran instead.  Watched by tests and `ca status`.
        self.spill_stats = {"inline": 0, "background": 0}
        if mode == "driver" and not client_mode:
            # plasma-style pre-allocation: warm an arena while the driver is
            # still bootstrapping so early puts land in pre-faulted pages.
            # Client mode skips it: its local store only caches pulled
            # copies, and puts upload to the head instead
            self.shm_store.warm()
        self.fn_manager = FunctionManager()
        self.reference_counter = ReferenceCounter(self._flush_refs)
        # --- ownership plane (core/ownership.py) --------------------------
        # This process is the lifetime authority for the objects it creates:
        # its OwnerLedger holds their cluster-wide borrower sets, and other
        # processes settle inc/dec against it over direct connections.  The
        # head keeps only the registry (obj_created/obj_release) and adopts
        # orphaned ledgers on owner death (owner_sync digests).  Client-mode
        # drivers have no ledger — their puts are hosted (and their holders
        # kept) by the head — but still ROUTE updates for borrowed refs to
        # the owning worker over TCP.
        self._owner_plane = bool(getattr(self.config, "owner_plane", True))
        self.owner_ledger: Optional[OwnerLedger] = None
        if self._owner_plane and not client_mode:
            self.owner_ledger = OwnerLedger(
                self.client_id,
                on_clear=self._ledger_clear,
                on_pin_zero=self._ledger_pin_zero,
                pending_grace_s=getattr(self.config, "early_ref_grace_s", 600.0),
            )
        # borrowed oid -> owner client id (fed by ObjectRef rehydration);
        # routes that ref's inc/dec/pins to the owner's ledger.  NEVER
        # dropped eagerly — a value pin's release can fire from GC long
        # after the handle died, and misrouting it to the head would strand
        # the holder in the owner's ledger.  Pruned periodically instead
        # (housekeeping), skipping oids with live handles or queued updates;
        # pin callbacks re-seed their captured owner when they fire late.
        self._borrowed_owners: Dict[bytes, str] = {}
        # obj_release notifies that found the head down: re-sent by
        # housekeeping once the head is back (lifetime already settled —
        # only the registry record and remote copies remain to clean)
        self._deferred_releases: List[list] = []
        # obj_copy notifies that found the head down/unreachable: re-sent by
        # housekeeping so the directory eventually learns about pulled
        # copies (multi-source pulls split across them; eviction reclaims
        # them by name)
        self._deferred_copy_notifies: List[tuple] = []
        self._last_owner_sync = 0.0
        self._last_ledger_sweep = 0.0
        self._last_borrow_prune = 0.0
        self._owner_sync_full = True  # first sync after (re)connect is full
        # evict the cache when the last local ref drops: cached values hold
        # zero-copy views, which hold arena value-pins — without eviction,
        # pinned slices would never be reusable.  Owned INLINE values (no shm
        # backing) are kept: they are the only copy and stay resolvable
        self.reference_counter.set_on_zero(self._evict_on_zero)
        self._put_counter = _Counter()
        self._task_counter = _Counter()
        self.head: Optional[Connection] = None
        self._conns: Dict[str, Connection] = {}
        self._connecting: Dict[str, asyncio.Future] = {}
        self._lease_pools: Dict[tuple, LeasePool] = {}
        self._actor_addr_cache: Dict[str, Tuple[str, int]] = {}  # aid -> (addr, incarnation)
        self.total_resources: Dict[str, float] = {}
        # in-flight node-to-node object pulls, deduped by oid
        self._pulls: Dict[bytes, asyncio.Future] = {}
        # slices already spilled but whose memory awaits the last pin drop
        self._spilled_pinned: set = set()
        # in-flight streaming generators (ObjectRefGenerator consumers)
        self._streams: Dict[bytes, Any] = {}
        # cancellation (task_manager.h CancelTask role): task ids the owner
        # cancelled, and where each in-flight push currently executes
        self._cancelled_tasks: set = set()
        self._inflight_tasks: Dict[bytes, str] = {}  # task_id -> worker addr
        # drain plane: node_id -> monotonic expiry of the preemption window.
        # Fed by "drain" pubs from the head; worker/lease deaths on a node
        # inside its window are SYSTEM failures — retried without consuming
        # the task's max_retries budget (see _retry_exempt)
        self._draining_nodes: Dict[str, float] = {}
        # lineage: task specs of submitted normal tasks, so a lost object can
        # be recomputed by re-executing its creating task (object_recovery_
        # manager.h).  Holding the original arg ObjectRefs here pins the
        # dependency chain (lineage pinning).  FIFO-capped.
        self._lineage: Dict[bytes, dict] = {}
        self._lineage_order: deque = deque()
        self._recon_lock = threading.Lock()
        self._recon_events: Dict[bytes, threading.Event] = {}
        # device object table: oid-bytes -> live device value (owner side)
        self.device_objects: Dict[bytes, Any] = {}
        # --- p2p planes (ownership directory + direct collectives) --------
        # collective mailbox: (group, key, src_rank) -> (data, shape, dtype)
        # deliveries land on the IO loop (coll_push RPC); rank threads block
        # in coll_wait.  Bounded by op lockstep + cleared on group close.
        self._coll_cond = threading.Condition()
        self._coll_mail: Dict[Tuple[str, str, int], tuple] = {}
        # owner-addr cache for p2p location resolution: client_id ->
        # Connection-able addr (None = owner unreachable/non-serving; the
        # head fallback handles it).  One head lookup per OWNER, not per
        # object.
        # owner -> (addr | None, expiry | None): positive entries live for
        # the session, negative ones expire so transient head failures
        # don't permanently disable the p2p/owner path for a healthy peer
        self._owner_addr_cache: Dict[str, Tuple[Optional[str], Optional[float]]] = {}
        self._p2p_server = None  # driver-mode mini server (workers use theirs)
        self.current_task_id: Optional[TaskID] = None
        self.current_actor_id: Optional[ActorID] = None
        # submission pump: user threads enqueue coroutine factories here; one
        # threadsafe wakeup drains many submissions (hot-path amortization)
        self._submit_queue: deque = deque()
        self._submit_wakeup_pending = False
        self._submit_lock = threading.Lock()
        # refcount piggyback/debounce: every obj_refs update (owner counts,
        # value pins, transit pins) coalesces into this per-holder dirty map
        # on the IO loop and flushes as ONE notify per holder after a short
        # timer — a 4k-object burst of inc/dec churn becomes a handful of
        # logical messages riding the outgoing batch envelopes instead of a
        # message per object.  Keyed (as_id, ttl); values {"inc": set,
        # "dec": set}.
        self._ref_pending: Dict[tuple, dict] = {}
        self._ref_flush_scheduled = False
        # pre-encoded task-spec templates for the argless fast paths, keyed by
        # the spec's constant fields (fn/actor+method, num_returns, retriable)
        self._spec_templates: Dict[tuple, MsgTemplate] = {}
        # lease-plane directory cache: (fetched_at, entries|None).  Entries
        # survive head outages (stale beats nothing: agents keep granting
        # while the control plane restarts); refreshed at most once per
        # lease_dir_ttl_s and only while a pool is growing.
        self._lease_dir_cache: Tuple[float, Optional[list]] = (0.0, None)
        # fn_ids whose blob was already inlined per worker connection during
        # a head outage: one delivery per (conn, fn) — the worker caches the
        # definition, so repeating the blob on every push of a flood would
        # just multiply frame size (weak-keyed: dies with the connection)
        import weakref

        self._conn_fn_sent: "weakref.WeakKeyDictionary[Connection, set]" = (
            weakref.WeakKeyDictionary()
        )
        self._stopped = False
        self._head_fenced = False  # head refused/fenced this process: must exit
        # hook for the worker-process host: invoked (on the IO loop) the
        # moment a fence verdict lands, so zombie tasks are cancelled
        # immediately instead of on the next watch tick
        self._on_fenced_cb: Optional[Any] = None
        # head-redial backoff (jittered): a head restart with N workers must
        # not produce a synchronized reconnect storm on a fixed tick
        self._redial_attempts = 0
        self._redial_next = 0.0
        # network-chaos plane: per-link partition/straggler injection (spec
        # from config at start; runtime `ca chaos set` arrives as pushes)
        netchaos.maybe_install_from_config(self.config, self.node_id)
        # flight recorder: journal this process's plane decisions; slices
        # ship on the metrics-delta piggyback (util/metrics.flush_once)
        if getattr(self.config, "flightrec_plane", True):
            from ..util import flightrec, metrics as _metrics

            flightrec.init(
                cap=getattr(self.config, "flightrec_ring_len", 4096),
                node_id=self.node_id, proc=self.client_id,
            )
            # the journal ships on the metrics flush: arm the flusher now —
            # a process that never mints a Metric must still ship its events
            _metrics._ensure_flusher()
        # log plane: lazily-built printer for log_batch pushes (drivers
        # subscribed via log_sub; see util/logplane.DriverLogPrinter)
        self._log_printer = None
        self._external_loop = loop is not None
        if loop is None:
            self.loop = asyncio.new_event_loop()
            # eager tasks (3.12+): submission coroutines usually run to their
            # first await synchronously, skipping a schedule round-trip per task
            if hasattr(asyncio, "eager_task_factory"):
                self.loop.set_task_factory(asyncio.eager_task_factory)
            self._io_thread = threading.Thread(
                target=self._run_loop, name="ca-io", daemon=True
            )
            self._io_thread.start()
        else:
            self.loop = loop
            self._io_thread = None

    # ------------------------------------------------------------- io thread
    def _run_loop(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run_coro(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the IO loop from a user thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def _pump_submit(self, coro_factory):
        """Enqueue a submission coroutine with one amortized loop wakeup."""
        with self._submit_lock:
            self._submit_queue.append(coro_factory)
            if self._submit_wakeup_pending:
                return
            self._submit_wakeup_pending = True
        try:
            self.loop.call_soon_threadsafe(self._drain_submit_queue)
        except RuntimeError:
            # loop closed (shutdown): drop the queued submission and surface
            # the error instead of hanging a future get()
            with self._submit_lock:
                self._submit_queue.clear()
                self._submit_wakeup_pending = False
            raise RuntimeError("cannot submit work: runtime is shut down")

    def _drain_submit_queue(self):
        with self._submit_lock:
            items = list(self._submit_queue)
            self._submit_queue.clear()
            self._submit_wakeup_pending = False
        for factory in items:
            # a factory may complete synchronously (fast-path submission via
            # call_cb) and return None; only coroutines become tasks
            coro = factory()
            if coro is not None:
                task = spawn_bg(coro)
                task.add_done_callback(self._report_task_exc)

    @staticmethod
    def _report_task_exc(task):
        """Done-callback for fire-and-forget submissions (asyncio tasks and
        concurrent futures alike)."""
        if not task.cancelled():
            exc = task.exception()
            if exc is not None:
                import traceback

                print(
                    f"[ca] internal submission error: {exc!r}\n"
                    + "".join(traceback.format_exception(exc)),
                    flush=True,
                )

    # ------------------------------------------------------------- bootstrap
    def connect(self):
        self.run_coro(self.connect_async(), timeout=30)

    async def connect_async(self):
        if self.mode == "driver" and not self.client_mode and self.serve_addr is None:
            # the driver serves the p2p planes too (owner_locate for objects
            # it owns, coll_push for collective ranks) — in the reference
            # every worker INCLUDING the driver runs a core-worker gRPC
            # server (core_worker.h); without one, every driver-owned ref
            # resolution would fall back to polling the head
            await self._start_p2p_server()
        self.head = await self._dial_head()
        self.head.set_push_handler(self._on_push)
        reply = await self.head.call(
            "register",
            role=self.mode,
            client_id=self.client_id,
            pid=os.getpid(),
            addr=self.serve_addr or self._p2p_addr() or "",
            addr_tcp=self.serve_addr_tcp or self._p2p_addr_tcp() or "",
            node_id=self.node_id,
            remote=self.client_mode,
        )
        self.total_resources = reply["resources"]
        self._adopt_register_reply(reply)
        self._maybe_log_sub(self.head)
        self._housekeeping_task = spawn_bg(self._housekeeping())

    async def _dial_head(self) -> Connection:
        """Dial the head address ring: each candidate once, starting at the
        current pick, rotating on failure.  Raises the last error when every
        candidate is down (callers treat that as 'head still restarting')."""
        from ..util.aio import dial  # lazy: util/__init__ reaches into core

        last: Optional[BaseException] = None
        for _ in range(max(1, len(self._head_ring))):
            addr = self._head_ring.current or self.head_sock
            netchaos.register_addr(addr, "n0")
            try:
                conn = await dial(addr, purpose="head", peer_node="n0")
            except asyncio.CancelledError:
                raise
            except Exception as e:
                last = e
                self._head_ring.rotate()
                continue
            # `addr` is the ring slot this dial succeeded against; a ring
            # merge landing during the dial must not retarget it:
            # ca-lint: ignore[async-await-race]
            self.head_sock = addr
            return conn
        raise last if last is not None else ConnectionError("no head address")

    def _adopt_register_reply(self, reply: dict) -> None:
        """Post-register adoption: worker processes stamp their node's
        incarnation AND the head authority epoch onto every head RPC (the
        fencing tokens — a stale ninc after a partition verdict, or a stale
        hep after a head failover, is refused before side effects land), and
        any active runtime chaos schedule is installed locally."""
        ep = reply.get("head_epoch")
        if ep is not None:
            self.head_epoch = max(self.head_epoch, int(ep))
        if reply.get("standbys"):
            # learn every promotion candidate for the next failover
            self._head_ring.merge(reply["standbys"])
        if self.mode == "worker":
            # set OR clear: a reply without node_inc (snapshotless head
            # restart racing the agent's rejoin) must not leave any prior
            # stamp semantics ambiguous on the fresh connection
            ni = reply.get("node_inc")
            stamp = {}
            if ni is not None:
                stamp["ninc"] = ni
            if ep is not None:
                stamp["hep"] = int(ep)
            self.head.stamp = stamp or None
        if reply.get("net_chaos"):
            try:
                netchaos.install(
                    reply["net_chaos"], self.node_id,
                    epoch=reply.get("net_chaos_epoch"),
                )
            except (ValueError, TypeError):
                pass

    def _maybe_log_sub(self, conn) -> None:
        """Subscribe this driver to the cluster log stream (log plane):
        remote workers' prints land on our stdout/stderr with attribution.
        init(log_to_driver=False) opts out."""
        if self.mode != "driver" or not getattr(self.config, "log_to_driver", True):
            return
        try:
            conn.notify("log_sub")
        except Exception:
            pass

    def _on_log_batch(self, msg) -> None:
        printer = self._log_printer
        if printer is None:
            from ..util.logplane import DriverLogPrinter

            printer = self._log_printer = DriverLogPrinter()
        try:
            printer.print_records(msg.get("records") or ())
        except Exception:
            pass  # a printing hiccup must never take down the read loop

    async def _on_push(self, msg):
        if msg.get("m") == "log_batch":
            self._on_log_batch(msg)
            return
        if msg.get("m") == "fenced":
            # the head refused an RPC stamped with our (stale) node
            # incarnation: this process was declared dead — stop acting
            from .ownership import warn_ratelimited

            warn_ratelimited(
                "worker-fenced",
                f"head fenced this process (node {msg.get('node_id')} "
                f"incarnation {msg.get('ninc')} superseded): cancelling "
                f"zombie tasks and exiting",
            )
            self._fence_now()
            return
        if msg.get("m") == "net_chaos":
            # runtime chaos broadcast (`ca chaos set`)
            try:
                netchaos.install(
                    msg.get("spec") or "", self.node_id,
                    epoch=msg.get("epoch"),
                )
            except (ValueError, TypeError):
                pass
            return
        if msg.get("m") == "ha_ring":
            # runtime standby-ring dissemination (HA plane): learn failover
            # targets that subscribed after this worker registered
            self._head_ring.merge(msg.get("standbys") or [])
            ep = msg.get("head_epoch")
            if ep is not None and int(ep) > self.head_epoch:
                self.head_epoch = int(ep)
            return
        if msg.get("m") == "owner_refs":
            # the head settling against THIS owner's ledger: releasing a
            # settled ledgerless (client-mode) container's containment edges
            # (head._release_cnt_pairs), or relaying a borrower's inc/dec/pin
            # that fell back to it while we were transiently unreachable
            # (head._forward_to_owner)
            self.serve_owner_refs(
                msg.get("inc"), msg.get("dec"),
                msg.get("as_id") or "head", bool(msg.get("ttl")),
            )
            return
        if msg.get("m") == "owner_transit_done":
            # relayed receiver ack for a transit pin held in this ledger
            self.serve_owner_transit_done(
                msg["token"], msg.get("oids"), msg.get("cid", "?"),
                msg.get("register", True),
            )
            return
        if msg.get("m") != "pub":
            return
        ch = msg.get("ch")
        if ch == "actors":
            data = msg.get("data") or {}
            aid = data.get("actor_id")
            if (
                aid and data.get("addr") and not self.client_mode
                and data.get("state") == "alive"
            ):
                # remote clients can't use pub'd (unix) addrs; they refresh
                # through get_actor, which maps to the TCP dual.  Only an
                # alive incarnation may land in the cache: dead/restarting
                # pubs can still carry the old worker's addr
                self._actor_addr_cache[aid] = (data["addr"], data.get("incarnation", 0))
            elif aid and data.get("state") in ("restarting", "dead"):
                # drop the stale route immediately instead of waiting for a
                # failed dial to trigger the get_actor refresh
                self._actor_addr_cache.pop(aid, None)
        elif ch == f"shm_free:{self.client_id}":
            data = msg.get("data") or {}
            name = data.get("shm_name")
            if name:
                self.shm_store.free_local(name)
        elif ch == "drain":
            self._on_drain_pub(msg.get("data") or {})
        elif ch == "nodes":
            data = msg.get("data") or {}
            if data.get("alive") is False and data.get("node_id"):
                self._on_node_dead_pub(data["node_id"])
        elif ch == "client_gone":
            # a borrower process died: its holder ids, value pins, transit
            # tokens, and containment edges in this owner's ledger can never
            # dec — purge them (the head does the same for its own records)
            gone = (msg.get("data") or {}).get("client_id")
            if gone:
                if self.owner_ledger is not None:
                    self.owner_ledger.purge_holder(gone)
                # in-flight owner routing to it should fail over to the head
                self._owner_addr_cache[gone] = (
                    None, time.monotonic() + self._OWNER_ADDR_NEG_TTL
                )
        elif ch == "lease_reclaim":
            # another client's lease request is queued: return surplus idle
            # leases NOW instead of after the idle timeout, and shed down to
            # the head's fair-share cap as pipelines drain (multi-client
            # fairness — without this, client batches serialize on ~1s gaps)
            cap = (msg.get("data") or {}).get("cap")
            to_return = []
            for pool in self._lease_pools.values():
                if pool.pg is not None:
                    # PG leases return to the placement group's own
                    # reservation, never to free cluster capacity — shedding
                    # them can't satisfy the contending client and only costs
                    # this client re-acquisition latency
                    continue
                if cap is not None:
                    pool.contended_cap = int(cap)
                    pool.contended_until = time.monotonic() + 1.0
                to_return.extend(pool.reap_contended())
            self.return_leases(to_return)

    # drain kills may land a little after the announced deadline (the head's
    # monitor tick, worker teardown): the retry exemption outlives it by this
    _DRAIN_GRACE_S = 15.0

    def _on_drain_pub(self, data: dict) -> None:
        """Head announced a node drain (preemption warning, `ca drain`,
        autoscaler downscale).  From now until the deadline (+grace), any
        worker death on that node is a system failure: retries are exempt
        from the user's max_retries budget.  Idle leases on the node are
        returned immediately so new tasks land on survivors."""
        nid = data.get("node_id")
        if not nid:
            return
        window = float(data.get("deadline_s") or 0.0) + self._DRAIN_GRACE_S
        self._draining_nodes[nid] = time.monotonic() + window
        from ..util import flightrec

        if flightrec.REC is not None:
            flightrec.REC.record(
                "drain", "drain_pub", target_node=nid,
                reason=data.get("reason"), deadline_s=data.get("deadline_s"),
            )
        # steer new local grants away: the cached lease directory may name
        # the draining agent for up to a TTL — drop it now
        ts, entries = self._lease_dir_cache
        if entries:
            self._lease_dir_cache = (
                ts, [e for e in entries if e.get("node_id") != nid]
            )
        recalled = []
        for pool in self._lease_pools.values():
            recalled.extend(pool.reap_node(nid))
        if recalled:
            DRAIN_STATS["leases_recalled_total"] += len(recalled)
            self.return_leases(recalled)

    def _fence_now(self) -> None:
        """A death verdict landed (refused re-register, FencedError reply,
        or a `fenced` push): this process must stop acting on anything
        minted under its dead incarnation.  Sets the fence flag and fires
        the host callback — the worker process cancels its RUNNING zombie
        tasks immediately (side effects must not complete) instead of
        waiting for the next watch-loop tick."""
        if self._head_fenced:
            return
        self._head_fenced = True
        from ..util import flightrec

        if flightrec.REC is not None:
            flightrec.REC.record(
                "fence", "fenced", client_id=self.client_id,
            )
        cb = self._on_fenced_cb
        if cb is not None:
            try:
                cb()
            except Exception:
                pass

    def _on_node_dead_pub(self, nid: str) -> None:
        """The head declared a node dead (crash, or a partition verdict).
        A partitioned worker's socket never closes by itself — frames just
        vanish — so in-flight pushes toward that node would hang forever.
        Drop its leases, purge it from the cached lease directory, and
        close our connections to its workers NOW: pending push_task calls
        fail with ConnectionError and the normal retry machinery resubmits
        on surviving capacity."""
        if nid == self.node_id:
            return  # our own node: the fence/register path governs us
        dead_addrs = set()
        for pool in self._lease_pools.values():
            hit = False
            for l in pool.leases:
                if l.node == nid and not l.dead:
                    dead_addrs.add(self._normalize_peer_addr(l.addr))
                    l.dead = True  # busy or idle: never pick/return it again
                    hit = True
            if hit:
                pool.leases = [l for l in pool.leases if not l.dead]
        ts, entries = self._lease_dir_cache
        if entries:
            self._lease_dir_cache = (
                ts, [e for e in entries if e.get("node_id") != nid]
            )
        for addr in dead_addrs:
            conn = self._conns.pop(addr, None)
            if conn is not None and not conn.closed:
                spawn_bg(conn.close())

    def draining_node_ids(self) -> set:
        """Node ids currently inside an announced drain window (fed by the
        head's `drain` pubs; entries expire at deadline+grace).  The serve
        controller reads this to stop routing to / start replacing replicas
        on exiting nodes with ZERO extra head RPCs.  Thread-safe snapshot."""
        now = time.monotonic()
        return {n for n, exp in dict(self._draining_nodes).items() if exp > now}

    def _retry_exempt(self, node_id: Optional[str]) -> bool:
        """Is a worker death on `node_id` inside a drain window?  Exempt
        retries don't consume max_retries (announced exits are the system's
        fault, not the app's)."""
        if not node_id:
            return False
        exp = self._draining_nodes.get(node_id)
        if exp is None:
            return False
        if time.monotonic() > exp:
            del self._draining_nodes[node_id]
            return False
        return True

    async def _housekeeping(self):
        period = 0.25
        last_touch = time.monotonic()
        while not self._stopped:
            await asyncio.sleep(period)
            now = time.monotonic()
            if self.client_mode and now - last_touch > 30:
                # keep the client session dir's mtime fresh so another
                # ca.init on this host's stale-session sweep (api.py
                # _sweep_stale_sessions, 1h horizon) never reaps a live
                # client's scratch/pull-cache out from under it
                last_touch = now
                try:
                    os.utime(self.session_dir)
                except OSError:
                    pass
            if self.head is not None and self.head.closed and not self._head_fenced:
                # head died (restart-in-progress): keep redialing; the
                # restarted head re-adopts us from its snapshot.  Jittered
                # exponential backoff: a head restart with N workers on a
                # fixed tick produced a synchronized reconnect storm
                if now >= self._redial_next:
                    if await self._reconnect_head():
                        self._redial_attempts = 0
                        self._redial_next = 0.0
                    else:
                        self._redial_attempts += 1
                        self._redial_next = now + _redial_backoff(
                            self._redial_attempts
                        )
            to_return = []
            for pool in self._lease_pools.values():
                to_return.extend(pool.reap_idle(now, self.config.lease_idle_timeout_s))
            self.return_leases(to_return)
            if self._draining_nodes:
                # expired preemption windows (the node is gone or the drain
                # completed long ago) stop excluding/exempting
                self._draining_nodes = {
                    n: t for n, t in self._draining_nodes.items() if t > now
                }
            self.reference_counter.flush()
            if (
                self._deferred_copy_notifies
                and self.head is not None
                and not self.head.closed
            ):
                # transfer plane: copies the directory missed (notify raced
                # a head restart).  Dropped-meanwhile copies are skipped —
                # advertising a freed slice would feed multi-source pulls a
                # dead source.
                pend, self._deferred_copy_notifies = (
                    self._deferred_copy_notifies, [],
                )
                for oid_b, name in pend:
                    if not self.shm_store.is_local(name):
                        continue
                    try:
                        self.head.notify(
                            "obj_copy", oid=oid_b, node=self.node_id,
                            shm_name=name,
                        )
                    except Exception:
                        self._deferred_copy_notifies.append((oid_b, name))
            if self.owner_ledger is not None:
                self._owner_plane_tick(now)
            if (
                self._owner_plane
                and len(self._borrowed_owners) > 4096
                and now - self._last_borrow_prune > 10.0
            ):
                # bound the borrowed-owner map: drop routing entries for
                # oids with no live handle, no cached entry, and no queued
                # update (late pin releases re-seed their captured owner)
                self._last_borrow_prune = now
                queued: set = set()
                for ent in self._ref_pending.values():
                    queued |= ent["inc"]
                    queued |= ent["dec"]
                for oid_b in list(self._borrowed_owners):
                    o = ObjectID(oid_b)
                    if (
                        oid_b not in queued
                        and self.reference_counter.local_count(o) == 0
                        and self.memory_store.get_entry(o) is None
                    ):
                        del self._borrowed_owners[oid_b]
            self._flush_task_events()

    _TASK_EVENTS_CHUNK = 5000  # bounded notify frames after a long restage

    def _flush_task_events(self):
        """Ship buffered lifecycle/span events to the head's task_events ring
        (IO loop only).  Events drained while the head is unreachable are
        re-staged, not lost.  Sent in bounded chunks: a buffer that grew
        toward the cap during a head outage must not become one giant frame
        that stalls the IO loop right as the cluster recovers."""
        from ..util import tracing

        if self.head is None or self.head.closed:
            return  # leave the buffer in place; no drain/restage churn
        events = tracing.drain_events()
        if not events:
            return
        chunk = self._TASK_EVENTS_CHUNK
        for i in range(0, len(events), chunk):
            try:
                self.head.notify("task_events", events=events[i : i + chunk])
            except Exception:
                tracing.restage_events(events[i:])
                return

    async def _reconnect_head(self) -> bool:
        """Redial and re-register with the head (gcs_client_reconnection
        analogue), walking the HA address ring on failure.  Sets
        _head_fenced if the head refuses us (it declared this worker dead —
        the process must exit, not retry)."""
        if not self.client_mode:
            # failover: a promoted standby rewrites the session's head.addr
            # — fold the current occupant into the ring before dialing, so
            # even a client configured with only the dead head's address
            # finds the successor
            try:
                cur = open(
                    os.path.join(self.session_dir, "head.addr")
                ).read().strip()
                if cur:
                    self._head_ring.merge([cur])
            except OSError:
                pass
        try:
            conn = await self._dial_head()
        except asyncio.CancelledError:
            raise
        except Exception:
            return False
        conn.set_push_handler(self._on_push)
        try:
            reply = await conn.call(
                "register",
                role=self.mode,
                client_id=self.client_id,
                pid=os.getpid(),
                # same fallbacks as the initial registration: the driver's
                # only serving socket is its p2p listener — dropping it here
                # made driver-owned inline objects unresolvable for
                # borrowers after a head restart
                addr=self.serve_addr or self._p2p_addr() or "",
                addr_tcp=self.serve_addr_tcp or self._p2p_addr_tcp() or "",
                node_id=self.node_id,
                remote=self.client_mode,
                timeout=5,
            )
        except asyncio.CancelledError:
            await conn.close()
            raise  # shutdown mid-redial: release the socket, stay cancelled
        except FencedError:
            await conn.close()
            self._fence_now()  # death verdict: cancel zombies, then exit
            return False
        except Exception as e:
            await conn.close()  # before anything that could raise (str(e) can)
            if "declared dead" in str(e):
                self._fence_now()
            else:
                # a standby's refusal (or any other register failure): try
                # the next ring candidate on the following tick
                self._head_ring.rotate()
            return False
        if _head_epoch_regressed(self.head_epoch, reply.get("head_epoch")):
            # a resurrected OLD head answered this redial: refuse it — we
            # already adopted a successor's epoch, and handing this zombie
            # our registration would fork the registry
            from ..util import flightrec

            if flightrec.REC is not None:
                flightrec.REC.record(
                    "ha", "ha_fence_old_head", client_id=self.client_id,
                    offered=int(reply.get("head_epoch") or 0),
                    known=self.head_epoch,
                )
            await conn.close()
            self._head_ring.rotate()
            return False
        self.head = conn
        self._adopt_register_reply(reply)
        # the restarted head lost its subscriber table: re-join the stream
        self._maybe_log_sub(conn)
        # ... and this owner's ledger digest: next owner_sync is a full one
        self._owner_sync_full = True
        return True

    # ----------------------------------------------------------- lease plane
    async def _lease_directory(self) -> list:
        """Where are the delegated lease blocks?  One head RPC per TTL while
        pools grow; zero in steady state (leases are reused/pipelined).  The
        cached directory is intentionally kept through head outages and RPC
        failures — the agents it names keep granting regardless."""
        ts, entries = self._lease_dir_cache
        now = time.monotonic()
        if entries is not None and now - ts < self.config.lease_dir_ttl_s:
            return entries
        if self.head is None or self.head.closed:
            return entries or []
        try:
            r = await self.head.call("lease_dir", timeout=5)
            entries = (r.get("nodes") or []) if r.get("delegation", True) else []
        except asyncio.CancelledError:
            raise
        except Exception:
            entries = entries or []  # keep stale; back off one TTL either way
        self._lease_dir_cache = (now, entries)
        return entries

    async def local_lease_grant(self, pool: str) -> Tuple[Optional[_Lease], bool]:
        """Ask node agents for a lease out of their delegated blocks (IO
        loop).  Returns (lease, lease_plane_active): tries agents
        most-free-first; a denial (exhausted block) or unreachable agent
        falls through to the next, then to (None, True) — the caller falls
        back to the head.  (None, False) means NO delegated blocks exist
        (single-node cluster, delegation off): the caller must behave
        exactly like the classic central path — no probe ttl, no growth
        capping — or head-only topologies lose demand signal and
        concurrency."""
        entries = await self._lease_directory()
        if not entries:
            return None, False
        from . import scheduling

        denied = False
        for ent in scheduling.rank_delegation(
            entries, pool, exclude=self._draining_nodes
        ):
            try:
                conn = await self.conn_to(ent["addr"])
                r = await conn.call("lease_grant", pool=pool, timeout=5)
            except asyncio.CancelledError:
                raise
            except Exception:
                continue  # agent gone: the head's node-death path reclaims
            blk = (ent.get("pools") or {}).get(pool)
            if r.get("granted"):
                if blk is not None:  # optimistic: steer the next grant away
                    blk["used"] = blk.get("used", 0) + 1
                # chaos labeling: pushes to this worker belong to its node's
                # link (a partitioned node's pushes must vanish, not error)
                netchaos.register_addr(r["addr"], ent.get("node_id"))
                netchaos.register_addr(
                    self._normalize_peer_addr(r["addr"]), ent.get("node_id")
                )
                return _Lease(
                    r["lease_id"], r["worker_id"], r["addr"],
                    granter=ent["addr"], node=ent.get("node_id"),
                    inc=r.get("ninc"),
                ), True
            denied = True
            if blk is not None:
                blk["used"] = blk.get("size", 0)
        if denied:
            LEASE_STATS["local_denied"] += 1
            # the cached occupancy lied (all blocks full): refresh eagerly on
            # the next growth attempt instead of waiting out the TTL
            self._lease_dir_cache = (0.0, self._lease_dir_cache[1])
        return None, True

    def _fn_blob_for_push(self, conn: Connection, fn_id: bytes) -> Optional[bytes]:
        """Function blob to inline into a push, or None.  Only while the head
        (the normal blob directory) is down, and only ONCE per (connection,
        fn): the worker caches the definition after the first delivery, and
        concurrent pushes that race the first load fall into the worker's
        fetch-retry loop, which rechecks its local cache."""
        if self.head is not None and not self.head.closed:
            return None
        sent = self._conn_fn_sent.get(conn)
        if sent is None:
            sent = set()
            self._conn_fn_sent[conn] = sent
        if fn_id in sent:
            return None
        blob = self.fn_manager.blob_for(fn_id)
        if blob is not None:
            sent.add(fn_id)
        return blob

    def return_leases(self, leases: List[_Lease]) -> None:
        """Give leases back to their granters, grouped per plane: head
        leases ride one return_lease notify; agent-granted leases go back to
        their agent as lease_release.  A granter we can no longer reach
        needs nothing — both planes sweep leases on client disconnect and
        worker death (IO loop only)."""
        if not leases:
            return
        by_granter: Dict[Optional[str], List[str]] = {}
        for l in leases:
            by_granter.setdefault(l.granter, []).append(l.lease_id)
        for granter, lids in by_granter.items():
            if granter is None:
                if self.head is not None and not self.head.closed:
                    try:
                        self.head.notify("return_lease", lease_ids=lids)
                        LEASE_STATS["head_released"] += len(lids)
                    except Exception:
                        pass
            else:
                conn = self._conns.get(self._normalize_peer_addr(granter))
                if conn is not None and not conn.closed:
                    try:
                        conn.notify("lease_release", lease_ids=lids)
                        LEASE_STATS["local_released"] += len(lids)
                    except Exception:
                        pass

    def _flush_refs(self, inc: List[bytes], dec: List[bytes]):
        self._queue_refs(inc, dec)

    # ------------------------------------------------- refcount coalescing
    def _queue_refs(self, inc, dec, as_id: Optional[str] = None, ttl: bool = False):
        """Queue an obj_refs update from any thread (debounced send)."""
        try:
            self.loop.call_soon_threadsafe(
                self._queue_refs_on_loop, inc, dec, as_id, ttl
            )
        except RuntimeError:
            pass  # loop closed (shutdown)

    def _queue_refs_on_loop(self, inc, dec, as_id=None, ttl=False):
        """IO-loop half: merge into the dirty map and arm the flush timer.

        Merge rules (per holder id):
          - inc then dec in one window are BOTH kept — the head must process
            the add before the release, or `owner_released` (which only a dec
            from the owner sets) would never fire and the object would leak.
            The flush ships every inc of the window before any dec
            (two-phase), so the pair arrives in the safe order.
          - dec then inc (drop to zero, then a revived handle) CANCEL: the
            process holds the object again, and the head never stopped
            thinking so.  Shipping both would instead release a ref we
            still hold.
        """
        key = (as_id, ttl)
        ent = self._ref_pending.get(key)
        if ent is None:
            ent = self._ref_pending[key] = {"inc": set(), "dec": set()}
        else:
            WIRE_STATS["refcount_flushes_suppressed"] += 1
        for oid in inc:
            if oid in ent["dec"]:
                # a pending release followed by a revival: cancel the dec —
                # whatever inc state the window already carries is again the
                # truth (covers dec→inc and inc→dec→inc alike)
                ent["dec"].discard(oid)
            else:
                ent["inc"].add(oid)
        ent["dec"].update(dec)
        if not self._ref_flush_scheduled:
            self._ref_flush_scheduled = True
            self.loop.call_later(self._REFS_FLUSH_DELAY_S, self._flush_ref_pending)

    def _flush_ref_pending(self):
        """Settle the coalesced obj_refs updates with each object's lifetime
        AUTHORITY (ownership plane): oids this process owns apply directly
        to its own OwnerLedger (no IO at all); borrowed oids ride a direct
        `owner_refs` notify to the owner process's ledger; only oids with no
        known live owner — plane off, owner unknown, owner unreachable/dead
        — fall back to the head's centralized obj_refs path, which is also
        the failover authority after the head adopts a dead owner's ledger.

        Two phases per destination — every inc of the window ships before
        any dec — because holder keys are flushed independently and a dec
        that reaches an authority before a DIFFERENT key's inc for the same
        object could GC it under a live pin (the late inc would strand in
        the pending-refs grace buffer).  Promoting an inc is always safe: at
        worst the object lives until its paired dec in a later message of
        the same flush, processed in socket order.  Destinations need no
        cross-ordering: one object has exactly one authority."""
        self._ref_flush_scheduled = False
        if not self._ref_pending:
            return
        pending, self._ref_pending = self._ref_pending, {}
        if not self._owner_plane:
            self._send_head_refs(list(pending.items()))
            return
        # partition each (as_id, ttl) window's oids by authority
        local: List[tuple] = []   # (as_id, ttl, inc, dec) for my own ledger
        remote: Dict[str, List[tuple]] = {}  # owner cid -> windows
        central: List[tuple] = []  # head fallback
        for (as_id, ttl), ent in pending.items():
            buckets: Dict[Optional[str], List[List[bytes]]] = {}
            for oid in ent["inc"]:
                buckets.setdefault(self._ref_dest(oid), [[], []])[0].append(oid)
            for oid in ent["dec"]:
                buckets.setdefault(self._ref_dest(oid), [[], []])[1].append(oid)
            for dest, (inc, dec) in buckets.items():
                win = (as_id, ttl, inc, dec)
                if dest == "":
                    local.append(win)
                elif dest is None:
                    central.append(win)
                else:
                    remote.setdefault(dest, []).append(win)
        led = self.owner_ledger
        if local:
            OWNER_STATS["refs_settled_local"] += len(local)
            # same two-phase discipline as the wire paths: every window's
            # inc applies before any window's dec, so a cross-key pair for
            # one object can never GC it under a live pin
            for as_id, ttl, inc, _dec in local:
                if inc:
                    led.apply(inc, [], as_id if as_id is not None else self.client_id, ttl)
            for as_id, _ttl, _inc, dec in local:
                if dec:
                    led.apply([], dec, as_id if as_id is not None else self.client_id)
        for owner, wins in remote.items():
            self._send_owner_refs(owner, wins)
        if central:
            OWNER_STATS["refs_head_fallback"] += len(central)
            self._send_head_refs([((a, t), {"inc": i, "dec": d})
                                  for a, t, i, d in central])

    # ------------------------------------------------------ ownership plane
    def _ref_dest(self, oid: bytes) -> Optional[str]:
        """Which authority settles this oid's holder updates: "" = this
        process's own ledger, a client id = that owner's ledger, None = the
        head (plane off / owner unknown / resurrection after settle)."""
        led = self.owner_ledger
        if led is not None and led.tracks(oid):
            return ""
        owner = self._borrowed_owners.get(oid)
        if owner is not None:
            return owner
        if led is not None and self.reference_counter.is_owned(ObjectID(oid)):
            return ""
        return None

    def note_borrowed_owner(self, oid_b: bytes, owner: str) -> None:
        """An ObjectRef handle for another process's object materialized
        here: remember who settles its counts (ObjectRef.__init__)."""
        if self._owner_plane and owner != self.client_id:
            self._borrowed_owners[oid_b] = owner

    def _send_head_refs(self, items) -> None:
        """The classic centralized path: obj_refs notifies to the head, all
        incs of the flush window before any dec (IO loop only)."""
        head = self.head
        if head is None or head.closed:
            return  # head down: same drop-on-floor as the pre-plane path
        for phase in ("inc", "dec"):
            for (as_id, ttl), ent in items:
                oids = list(ent[phase])
                if not oids:
                    continue
                fields: Dict[str, Any] = {phase: oids}
                if as_id is not None:
                    fields["as_id"] = as_id
                if ttl and phase == "inc":
                    fields["ttl"] = True
                try:
                    head.notify("obj_refs", **fields)
                except Exception:
                    pass

    def _send_owner_refs(self, owner: str, wins: List[tuple]) -> None:
        """Ship one flush window's updates to a borrowed object's owner over
        the direct worker<->worker connection (AddBorrowedObject /
        WaitForRefRemoved, owner-resident form).  A cached open connection
        sends synchronously; otherwise a background dial sends (or fails
        over to the head — the arbiter for unreachable/dead owners)."""
        hit = self._cached_owner_addr(owner)
        if hit is not None and hit[0] is not None:
            conn = self._conns.get(self._normalize_peer_addr(hit[0]))
            if conn is not None and not conn.closed:
                try:
                    self._notify_owner_refs(conn, wins)
                    return
                except Exception:
                    pass
        t = spawn_bg(self._send_owner_refs_async(owner, wins))
        t.add_done_callback(self._report_task_exc)

    def _notify_owner_refs(self, conn: Connection, wins: List[tuple]) -> None:
        OWNER_STATS["refs_sent_owner"] += 1
        for phase in (0, 1):  # inc windows before dec windows
            for as_id, ttl, inc, dec in wins:
                oids = inc if phase == 0 else dec
                if not oids:
                    continue
                fields: Dict[str, Any] = {
                    ("inc" if phase == 0 else "dec"): oids,
                    "as_id": as_id if as_id is not None else self.client_id,
                }
                if ttl and phase == 0:
                    fields["ttl"] = True
                conn.notify("owner_refs", **fields)

    async def _send_owner_refs_async(self, owner: str, wins: List[tuple]) -> None:
        try:
            addr = await self._owner_addr_async(owner)
            if addr is None:
                raise ConnectionError(f"owner {owner} not dialable")
            conn = await self.conn_to(addr)
            self._notify_owner_refs(conn, wins)
        except asyncio.CancelledError:
            raise
        except Exception:
            # owner unreachable or dead: the head is the failover authority
            # (it adopts the owner's ledger from the last synced digest)
            OWNER_STATS["refs_head_fallback"] += len(wins)
            self._send_head_refs([((a, t), {"inc": i, "dec": d})
                                  for a, t, i, d in wins])

    def serve_owner_refs(self, inc, dec, as_id, ttl: bool = False) -> None:
        """A borrower's inc/dec landing on this process's ledger (the
        owner-resident settle path; workerproc/_p2p server `owner_refs`)."""
        led = self.owner_ledger
        if led is None:
            return  # plane raced off (shutdown): the disconnect sweep settles
        OWNER_STATS["refs_recv"] += 1
        led.apply(list(inc or ()), list(dec or ()), as_id, bool(ttl))

    def serve_owner_transit_done(self, token, roids, cid, register=True) -> None:
        led = self.owner_ledger
        if led is not None:
            led.transit_done(token, list(roids or ()), cid, bool(register))

    def serve_owner_pin(self, oid_b: bytes, as_id: str) -> dict:
        """Atomic pin+locate served by the owner (obj_pin, owner-resident):
        the pin registers in the ledger under the same lock that reads the
        location, so a reader can never map a slice the owner's spiller is
        about to recycle."""
        led = self.owner_ledger
        loc = led.pin(oid_b, as_id) if led is not None else None
        if loc is None:
            return {"found": False}
        return {"found": True, "node": self.node_id, "owner": self.client_id, **loc}

    def _is_my_slice(self, shm_name: str) -> bool:
        """Can this process reclaim these bytes itself?  Its own arena
        slices (only the creating allocator may recycle a slice) and its
        node's dedicated segments qualify; everything else needs the head's
        reclaim routing (shm_free pubs / agent unlinks)."""
        if "@" in shm_name:
            fname = shm_name.split("@", 1)[0].rsplit("/", 1)[-1]
            return fname.startswith(f"arena_{self.client_id}_")
        return self.shm_store.is_local(shm_name)

    def _ledger_clear(self, cleared: List[tuple]) -> None:
        """An owned object's cluster-wide lifetime settled (owner released +
        last borrower gone): free what this process can locally, release
        containment edges on nested refs, and tell the head to drop the
        registry record and reclaim the remote copies.  With the head down
        the LOCAL reclaim still completes (the acceptance property: GC does
        not need the control plane); the registry release is deferred."""
        release: List[list] = []
        for oid, info in cleared:
            OWNER_STATS["owner_gc"] += 1
            freed: List[str] = []
            for name in (info.get("shm_name"), info.get("pending_free")):
                if name and self._is_my_slice(name):
                    try:
                        self.shm_store.free_local(name)
                    except Exception:
                        pass
                    self._spilled_pinned.discard(name)
                    freed.append(name)
            spill = info.get("spill_path")
            if spill and os.path.exists(spill):
                try:
                    os.unlink(spill)
                    freed.append("spill:" + spill)
                except OSError:
                    pass
            for ioid, iowner in info.get("contains") or ():
                # the container dies: its borrow-pins on nested objects die
                # with it, routed to each inner object's own authority
                if iowner and iowner != self.client_id:
                    self._borrowed_owners.setdefault(ioid, iowner)
                self._queue_refs(
                    [], [ioid], as_id=f"cnt:{self.client_id}:{oid.hex()}"
                )
            if info.get("registered"):
                release.append([oid, freed])
        if not release:
            return
        head = self.head
        if head is not None and not head.closed:
            try:
                head.notify("obj_release", rel=release)
                return
            except Exception:
                pass
        OWNER_STATS["owner_gc_head_down"] += len(release)
        self._deferred_releases.extend(release)

    def _ledger_pin_zero(self, oid: bytes) -> None:
        """Last zero-copy value pin dropped on an object this owner spilled:
        the old slice's memory comes back now (owner-side pending_free)."""
        led = self.owner_ledger
        name = led.pop_pending_free(oid) if led is not None else None
        if name and self._is_my_slice(name):
            try:
                self.shm_store.free_local(name)
            except Exception:
                pass
            self._spilled_pinned.discard(name)

    def _add_owned(self, oid: ObjectID) -> None:
        """Mint ownership: local refcount authority + a ledger entry, BEFORE
        any handle can leave the process (borrower registrations race only
        reconstruction re-registration, absorbed by the pending buffer)."""
        self.reference_counter.add_owned(oid)
        if self.owner_ledger is not None:
            self.owner_ledger.register(oid.binary())

    def _register_contains(self, container_b: bytes, nested: List[bytes]) -> None:
        """Containment edges for a container THIS process owns: each nested
        ref gains a "cnt:<my-cid>:<container>" holder at its own authority,
        and the ledger remembers the edge list so settling the container
        releases them (head-resident obj_contains when the plane is off)."""
        led = self.owner_ledger
        if not self._owner_plane:
            self._notify_threadsafe(
                "obj_contains", oid=container_b, refs=list(nested)
            )
            return
        if led is None or not led.tracks(container_b):
            # ledgerless owner (client mode): the HEAD is this container's
            # lifetime authority.  The edges still register at each inner
            # object's OWN authority (head-side holders would not protect
            # owner-resident inners), and the head remembers the (oid,
            # authority) pairs so it can release them when the container
            # settles there.  Pair authority mirrors where the inc actually
            # routes ("" = the head itself).
            pairs = []
            for ioid in nested:
                d = self._ref_dest(ioid)
                pairs.append([ioid, self.client_id if d == "" else (d or "")])
            self._queue_refs(
                list(nested), [],
                as_id=f"cnt:{self.client_id}:{container_b.hex()}",
            )
            self._notify_threadsafe(
                "obj_contains", oid=container_b, refs=list(nested),
                pairs=pairs,
            )
            return
        pairs = [
            (ioid, self._borrowed_owners.get(ioid) or self.client_id)
            for ioid in nested
        ]
        old = led.set_contains(container_b, pairs)
        edge = f"cnt:{self.client_id}:{container_b.hex()}"
        self._queue_refs(list(nested), [], as_id=edge)
        for ioid, iowner in old or ():
            if iowner and iowner != self.client_id:
                self._borrowed_owners.setdefault(ioid, iowner)
            self._queue_refs([], [ioid], as_id=edge)

    def result_contains_pairs(
        self, container_b: bytes, nested: List[bytes], owner: str
    ) -> Optional[list]:
        """Worker-side half of owner-resident containment for a task RETURN
        (the container's owner is the submitter): register the edges at each
        nested ref's authority under the SUBMITTER's edge id and hand back
        the (oid, owner) pairs to ship with the result, so the submitter's
        ledger can release them when the container settles.  Returns None on
        the centralized path (caller falls back to obj_contains)."""
        if not self._owner_plane:
            return None
        pairs = [
            [ioid, self._borrowed_owners.get(ioid) or self.client_id]
            for ioid in nested
        ]
        self._queue_refs(
            list(nested), [], as_id=f"cnt:{owner}:{container_b.hex()}"
        )
        return pairs

    def _adopt_result_contains(self, oid_b: bytes, res: dict) -> None:
        """Owner-side half: a task result carried containment pairs for a
        container this process owns.  Record them — or, if the container's
        lifetime already settled (fire-and-forget), release the edges right
        away so the nested objects don't leak a dead container's pins.  A
        LEDGERLESS owner (client mode) cannot do either itself: it forwards
        the pairs to the head — its containers' lifetime authority — which
        releases the owner-resident edges when the record settles there."""
        pairs = [
            (bytes(i), (o if isinstance(o, str) else None))
            for i, o in (res.get("contains") or ())
        ]
        if not pairs:
            return
        led = self.owner_ledger
        if led is None:
            self._notify_threadsafe(
                "obj_contains", oid=oid_b,
                refs=[i for i, _ in pairs],
                pairs=[[i, o or ""] for i, o in pairs],
            )
            return
        old = led.set_contains(oid_b, pairs)
        edge = f"cnt:{self.client_id}:{oid_b.hex()}"
        stale = pairs if old is None else old
        for ioid, iowner in stale:
            if iowner and iowner != self.client_id:
                self._borrowed_owners.setdefault(ioid, iowner)
            self._queue_refs([], [ioid], as_id=edge)

    def _owner_plane_tick(self, now: float) -> None:
        """Housekeeping leg of the ownership plane (IO loop): ledger sweeps
        (expired pending adds / lost transit acks), deferred registry
        releases, and the owner_sync digest — versioned deltas of this
        ledger so the head can adopt it if this process dies.  A reconnect
        resets to a full sync (the restarted head lost the digest)."""
        led = self.owner_ledger
        if now - self._last_ledger_sweep > 5.0:
            self._last_ledger_sweep = now
            expired = led.sweep(now)
            if expired:
                # grace-expired borrower registrations are the owner-side
                # symptom of the same ordering bug the head counts as
                # early_refs_expired — surface them the same way
                OWNER_STATS["pending_expired"] += expired
                warn_ratelimited(
                    "ledger-pending-expired",
                    f"{expired} pending borrower registration(s) expired "
                    "past the grace window (lost registration ordering?)",
                )
        head = self.head
        if head is None or head.closed:
            return
        if self._deferred_releases:
            rel, self._deferred_releases = self._deferred_releases, []
            try:
                head.notify("obj_release", rel=rel)
            except Exception:
                self._deferred_releases = rel + self._deferred_releases
        if now - self._last_owner_sync < self.config.owner_sync_period_s:
            return
        self._last_owner_sync = now
        full = self._owner_sync_full
        d = led.digest_delta(full=full)
        if d is None:
            return
        try:
            head.notify("owner_sync", **d)
        except Exception:
            return
        OWNER_STATS["syncs_sent"] += 1
        if full:
            OWNER_STATS["syncs_full"] += 1
            self._owner_sync_full = False

    def _normalize_peer_addr(self, addr: str) -> str:
        """Remote clients may receive TCP duals bound to a wildcard host
        (head_host=0.0.0.0): substitute the host we actually dialed the head
        on — the cluster host as seen from here."""
        if (
            self.client_mode
            and addr.startswith(("tcp:0.0.0.0:", "tcp:::"))
            and self.head_sock.startswith("tcp:")
        ):
            head_host = self.head_sock[4:].rpartition(":")[0]
            port = addr.rpartition(":")[2]
            return f"tcp:{head_host}:{port}"
        return addr

    # ---------------------------------------------------- p2p serving plane
    def _p2p_addr(self) -> Optional[str]:
        if self._p2p_server is not None:
            return next(
                (a for a in self._p2p_server.bound_addrs if a.startswith("unix:")),
                None,
            )
        return None

    def _p2p_addr_tcp(self) -> Optional[str]:
        if self._p2p_server is not None:
            return next(
                (a for a in self._p2p_server.bound_addrs if a.startswith("tcp:")),
                None,
            )
        return None

    async def _start_p2p_server(self):
        """Driver-mode RPC listener for the p2p planes.  Worker processes
        already serve these methods on their task server (workerproc._handle
        delegates here); the driver needs its own socket because it owns
        puts and task returns — the objects borrowers resolve most."""
        if self._p2p_server is not None:
            return  # connect_async re-entry must not stack listeners
        from .protocol import Server

        sock = os.path.join(self.session_dir, f"drv_{self.client_id}.sock")

        async def handle(state, msg, reply, reply_err):
            m = msg["m"]
            if m == "owner_locate":
                reply(**await self.owner_locate_async(msg["oid"]))
            elif m == "owner_refs":
                # borrower inc/dec settling against this driver's ledger
                self.serve_owner_refs(
                    msg.get("inc"), msg.get("dec"),
                    msg.get("as_id") or state.get("client_id", "?"),
                    bool(msg.get("ttl")),
                )
                reply()
            elif m == "owner_transit_done":
                self.serve_owner_transit_done(
                    msg["token"], msg.get("oids"), msg.get("cid", "?"),
                    msg.get("register", True),
                )
                reply()
            elif m == "owner_pin":
                reply(**self.serve_owner_pin(msg["oid"], msg["as_id"]))
            elif m == "coll_push":
                self.coll_deliver(
                    msg["group"], msg["key"], msg["src"],
                    msg["data"], msg["shape"], msg["dtype"],
                    msg.get("meta"),
                )
                reply()
            # operator liveness probe: ca-lint: ignore[rpc-dead-handler]
            elif m == "ping":
                reply(worker_id=self.client_id)
            else:
                reply_err(ValueError(f"unknown p2p method {m}"))

        self._p2p_server = Server([sock, "tcp:0.0.0.0:0"], handle)
        await self._p2p_server.start()

    def owner_locate_local(self, oid_b: bytes) -> dict:
        """Sync shim over owner_locate_async for off-loop callers (tests,
        diagnostics); the serve handlers await the async form directly."""
        return self.run_coro(self.owner_locate_async(oid_b), timeout=30)

    async def owner_locate_async(self, oid_b: bytes) -> dict:
        """Answer a borrower's location query from THIS process's authority
        over objects it owns (ownership_based_object_directory.h read path).

        shm-backed objects return their location; INLINE results (small task
        returns / puts, which never register at the head at all) are served
        by value — the owner is their only copy, and before this path
        existed a borrowed ref to a pending-then-inline result could only
        resolve if something promoted it.  Pending / device / spilled states
        report not-found: the borrower keeps waiting or falls back to the
        head (the arbiter for spill relocation and GC)."""
        e = self.memory_store.get_entry(ObjectID(oid_b))
        if e is None:
            # local read-cache evicted (owner's last handle died) while
            # borrowers still hold: the ledger remembers the primary copy
            led = self.owner_ledger
            info = led.entry_info(oid_b) if led is not None else None
            if info is not None and info.get("shm_name"):
                return {
                    "found": True,
                    "shm_name": info["shm_name"],
                    "size": info["size"],
                    "node": self.node_id,
                }
            return {"found": False}
        if e.state in ("shm", "value", "packed") and e.shm_name:
            if e.shm_name.startswith("spill:"):
                # relocated to disk: the head arbitrates spill reads
                return {"found": False}
            return {
                "found": True,
                "shm_name": e.shm_name,
                "size": e.size,
                "node": self.node_id,
            }
        if e.state in ("packed", "value"):
            # inline result served by value.  Nested ObjectRefs smuggled in
            # the payload need the same transit-pin protocol as task args
            # (_pack_with_transit_async): without a pin, the head may GC the
            # inner object between our reply and the borrower registering
            # its handle.  Packed blobs are re-packed through capture for
            # the same reason — the original pack ran before this borrower
            # existed.
            try:
                value = (
                    serialization.unpack(e.packed) if e.state == "packed"
                    else e.value
                )
                spec = await self._pack_with_transit_async(value, ttl_pin=True)
            except asyncio.CancelledError:
                raise
            except Exception:
                return {"found": False}
            return {"found": True, **spec}
        return {"found": False}

    def coll_deliver(
        self, group: str, key: str, src: int, data, shape, dtype, meta=None
    ):
        """Landing half of the p2p collective transport: a peer rank pushed
        a tensor chunk; wake any coll_wait blocked on it.  `meta` rides
        along untouched (quantized payloads carry their scales/shape there;
        the transport stays encoding-agnostic)."""
        with self._coll_cond:
            self._coll_mail[(group, key, int(src))] = (
                data, tuple(shape or ()), dtype, meta,
            )
            self._coll_cond.notify_all()

    def _coll_take(self, group: str, key: str, src: int, timeout: float):
        deadline = time.monotonic() + timeout
        k = (group, key, int(src))
        with self._coll_cond:
            while k not in self._coll_mail:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective recv timed out waiting for {k}"
                    )
                self._coll_cond.wait(min(remaining, 1.0))
            return self._coll_mail.pop(k)

    def coll_wait(self, group: str, key: str, src: int, timeout: float):
        """Block (rank thread) until the (group, key, src) chunk arrives."""
        import numpy as _np

        data, shape, dtype, _meta = self._coll_take(group, key, src, timeout)
        return _np.frombuffer(data, dtype=dtype).reshape(shape)

    def coll_wait_raw(self, group: str, key: str, src: int, timeout: float):
        """Raw-payload twin of coll_wait: returns (payload bytes, meta dict)
        without imposing an array interpretation — the quantized collective
        ring decodes its own wire format."""
        data, _shape, _dtype, meta = self._coll_take(group, key, src, timeout)
        return data, (meta or {})

    def coll_clear(self, group: str):
        with self._coll_cond:
            for k in [k for k in self._coll_mail if k[0] == group]:
                del self._coll_mail[k]

    def coll_push_start(
        self, addr: str, group: str, key: str, src: int, arr, timeout: float
    ):
        """Sending half: push one tensor chunk directly into a peer rank's
        mailbox over the worker TCP/unix dual — no head, no object store.
        Returns a concurrent future immediately (double-buffered ring
        pipelining: the caller overlaps this send with its own receive and
        joins later).  The payload is serialized HERE, on the caller's
        thread, so the caller may mutate `arr` the moment this returns."""
        import numpy as np

        arr = np.ascontiguousarray(arr)
        return self._coll_send_start(
            addr, group, key, src, arr.tobytes(), list(arr.shape),
            str(arr.dtype), None, timeout,
        )

    def coll_push_raw_start(
        self, addr: str, group: str, key: str, src: int,
        payload: bytes, meta: dict, timeout: float,
    ):
        """Raw-payload twin of coll_push_start (quantized ring steps)."""
        return self._coll_send_start(
            addr, group, key, src, payload, [], "raw", meta, timeout
        )

    def _coll_send_start(
        self, addr, group, key, src, data, shape, dtype, meta, timeout
    ):
        async def _send():
            conn = await self.conn_to(addr)
            fields = dict(
                group=group, key=key, src=int(src), data=data,
                shape=shape, dtype=dtype, timeout=timeout,
            )
            if meta is not None:
                fields["meta"] = meta
            await conn.call("coll_push", **fields)

        return asyncio.run_coroutine_threadsafe(_send(), self.loop)

    def coll_push_to(
        self, addr: str, group: str, key: str, src: int, arr, timeout: float
    ):
        """Blocking send (broadcast/send paths, where nothing overlaps)."""
        self.coll_push_start(addr, group, key, src, arr, timeout).result(
            timeout
        )

    async def _owner_addr_async(self, owner: Optional[str]) -> Optional[str]:
        """Resolve (and cache) the serving address of an object owner.
        Positive results cache for the session (one head lookup per owner
        process); None = owner can't be dialed right now (dead, remote
        client, unknown, or the head was briefly unreachable) — callers fall
        back to the head.  Negative results only cache for a short TTL so a
        transient head hiccup can't permanently disable the owner/p2p path
        for a healthy peer."""
        if not owner or owner == self.client_id:
            return None
        hit = self._cached_owner_addr(owner)
        if hit is not None:
            return hit[0]
        addr = None
        try:
            reply = await self.head.call("client_addr", client_id=owner)
            if reply.get("found"):
                if reply.get("node") == self.node_id:
                    addr = reply.get("addr") or reply.get("addr_tcp") or None
                else:  # cross-node: unix sockets don't travel
                    addr = reply.get("addr_tcp") or reply.get("addr") or None
        except asyncio.CancelledError:
            raise
        except Exception:
            addr = None
        self._owner_addr_cache[owner] = (
            (addr, None) if addr is not None
            else (None, time.monotonic() + self._OWNER_ADDR_NEG_TTL)
        )
        return addr

    def _cached_owner_addr(self, owner: str):
        """Live cache entry as a (addr,) 1-tuple, or None on miss/expiry —
        the single place the (addr, expiry) format is interpreted."""
        cached = self._owner_addr_cache.get(owner)
        if cached is not None:
            addr, expiry = cached
            if expiry is None or time.monotonic() < expiry:
                return (addr,)
        return None

    def _owner_addr(self, owner: Optional[str]) -> Optional[str]:
        if not owner or owner == self.client_id:
            return None
        hit = self._cached_owner_addr(owner)
        if hit is not None:
            return hit[0]
        return self.run_coro(self._owner_addr_async(owner), timeout=30)

    async def conn_to(self, addr: str) -> Connection:
        """One connection per peer.  Concurrent first-callers share a single
        connect (a stampede would create several sockets and destroy
        per-caller actor-call ordering across them)."""
        addr = self._normalize_peer_addr(addr)
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        pending = self._connecting.get(addr)
        if pending is not None:
            return await pending
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._connecting[addr] = fut
        try:
            from ..util.aio import dial  # lazy: util/__init__ reaches into core

            conn = await dial(addr, purpose="peer")
            conn.set_push_handler(self._on_peer_push)
            self._conns[addr] = conn
            fut.set_result(conn)
            return conn
        except BaseException as e:
            fut.set_exception(e)
            # mark retrieved for the no-other-waiter case (the creator
            # re-raises below), else GC logs "exception was never retrieved"
            # on every refused dial — e.g. racing a drained worker's address
            fut.exception()
            raise
        finally:
            del self._connecting[addr]

    # -------------------------------------------------------------- streaming
    async def _on_peer_push(self, msg):
        """Unsolicited frames from direct worker connections: streamed
        generator items (stream_item) land here in production order."""
        if msg.get("m") != "stream_item":
            return
        st = self._streams.get(msg["task_id"])
        if st is None:
            return  # stream abandoned
        idx = msg["idx"]
        oid = ObjectID.for_return(st.task_id, idx)
        self._add_owned(oid)
        self._store_results([oid], [msg["res"]], st.addr or "")
        st.on_item(idx)

    def stream_ack(self, st) -> None:
        """Consumer took one ref off the generator: advance the producer's
        backpressure window (thread-safe)."""
        def _send():
            conn = self._conns.get(st.addr)
            if conn is not None and not conn.closed:
                try:
                    conn.notify(
                        "stream_ack",
                        task_id=st.task_id.binary(),
                        consumed=st.next_read,
                    )
                except Exception:
                    pass

        try:
            self.loop.call_soon_threadsafe(_send)
        except RuntimeError:
            pass

    def cancel_stream(self, st) -> None:
        """Abandon one in-flight streaming task (ObjectRefGenerator.cancel):
        deliver a cancel to the executing worker so the producer generator
        stops, and drop the local stream state so late items are ignored
        (thread-safe; the _on_peer_push miss path treats unknown task ids as
        abandoned streams already)."""
        tid = st.task_id.binary()

        def _do():
            self._streams.pop(tid, None)
            self._cancelled_tasks.add(tid)
            addr = st.addr or self._inflight_tasks.get(tid)
            if addr is not None:
                conn = self._conns.get(self._normalize_peer_addr(addr)) or self._conns.get(addr)
                if conn is not None and not conn.closed:
                    try:
                        conn.notify("cancel", task_id=tid, force=False)
                    except Exception:
                        pass  # producer already gone: nothing left to stop

        try:
            self.loop.call_soon_threadsafe(_do)
        except RuntimeError:
            pass  # loop shutting down: the producer dies with the process

    def submit_streaming_task(self, fn, args, kwargs, opts: Dict[str, Any]):
        """Submit a generator task; returns an ObjectRefGenerator
        (_raylet.pyx ObjectRefGenerator analogue)."""
        from .streaming import ObjectRefGenerator, StreamState

        task_id = TaskID.for_normal_task(self.job_id)
        st = StreamState(task_id)
        self._streams[task_id.binary()] = st
        fn_id, blob = self.fn_manager.export(fn)
        if TRACE_HOOK is not None:
            _tr = TRACE_HOOK.begin_task_trace(
                task_id.hex(), getattr(fn, "__name__", "stream"), "task",
                self.client_id, self.node_id,
            )
            if _tr is not None:
                opts = dict(opts, _trace=_tr)
        self._pump_submit(
            lambda: self._submit_stream(task_id, st, fn_id, blob, args, kwargs, opts, None)
        )
        return ObjectRefGenerator(self, st, self.client_id)

    def submit_streaming_actor_task(self, actor_id: ActorID, method: str, args, kwargs, opts):
        from .streaming import ObjectRefGenerator, StreamState

        task_id = TaskID.for_actor_task(actor_id)
        st = StreamState(task_id)
        self._streams[task_id.binary()] = st
        opts = dict(opts, method=method)
        if TRACE_HOOK is not None:
            _tr = TRACE_HOOK.begin_task_trace(
                task_id.hex(), method, "actor_task", self.client_id, self.node_id
            )
            if _tr is not None:
                opts["_trace"] = _tr
        self._pump_submit(
            lambda: self._submit_stream(
                task_id, st, None, None, args, kwargs, opts, actor_id.hex()
            )
        )
        return ObjectRefGenerator(self, st, self.client_id)

    async def _submit_stream(self, task_id, st, fn_id, blob, args, kwargs, opts, actor_hex):
        """Slow-path push of a streaming task (no retries: replaying a
        partially consumed stream would duplicate side effects)."""
        lease = None
        pool = None
        try:
            if blob is not None:
                await self.head.call("register_function", fn_id=fn_id, blob=blob)
                self.fn_manager.mark_exported(fn_id)
            specs, kwspecs = await self._build_args(args, kwargs)
            if actor_hex is None:
                pool = self._lease_pool(opts)
                lease = await pool.acquire()
                addr = lease.addr
            else:
                addr = await self._actor_addr(actor_hex)
            st.addr = addr
            conn = await self.conn_to(addr)
            # cancellable like any pushed task: ca.cancel() needs the
            # executing worker's address to deliver the interrupt
            self._inflight_tasks[task_id.binary()] = self._normalize_peer_addr(addr)
            fields = dict(
                task_id=task_id.binary(),
                owner=self.client_id,
                args=specs,
                kwargs=kwspecs,
                num_returns="streaming",
                timeout=None,
            )
            trace = opts.get("_trace")
            if trace is not None:
                fields[TRACE_FIELD] = trace
                if TRACE_HOOK is not None:
                    TRACE_HOOK.record_task_event(
                        task_id.hex(), None,
                        "task" if actor_hex is None else "actor_task",
                        "SCHEDULED", trace=trace, worker_id=self.client_id,
                        node_id=self.node_id,
                    )
            if actor_hex is None:
                reply = await conn.call(
                    "push_task", fn_id=fn_id,
                    runtime_env=opts.get("runtime_env"), **fields,
                )
            else:
                reply = await conn.call(
                    "actor_call", actor_id=actor_hex, method=opts["method"], **fields
                )
            err = None
            if reply.get("stream_error") is not None:
                import pickle

                err = pickle.loads(reply["stream_error"])
            st.on_end(err)
        except asyncio.CancelledError:
            # unblock consumers before propagating the cancellation — a
            # swallowed cancel here would hang shutdown, a silent one would
            # hang the stream's readers
            st.on_end(TaskError("stream pump cancelled"))
            raise
        except BaseException as e:
            st.on_end(e if isinstance(e, CAError) else TaskError(repr(e)))
        finally:
            self._inflight_tasks.pop(task_id.binary(), None)
            if lease is not None:
                pool.release(lease, dead=False)
            self._streams.pop(task_id.binary(), None)

    # ------------------------------------------------------------------ put
    def new_owned_ref(self) -> ObjectRef:
        """Allocate a fresh owned ObjectRef with no value yet; the caller
        fulfills it later via memory_store.put_value/put_error (used by put()
        and by futures like PlacementGroup.ready())."""
        task_id = self.current_task_id or TaskID.for_normal_task(self.job_id)
        oid = ObjectID.for_put(task_id, self._put_counter.next())
        self._add_owned(oid)
        return ObjectRef(oid, owner=self.client_id, worker=self)

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed")
        ref = self.new_owned_ref()
        self._put_value(ref.id, value)
        return ref

    def _put_value(self, oid: ObjectID, value: Any):
        if _is_device_value(value):
            self.device_objects[oid.binary()] = value
            self.memory_store.put_value(oid, value)
            return
        with serialization.ref_capture() as nested:
            data, buffers = serialization.serialize(value)
        raws = [b.raw() for b in buffers]
        total = len(data) + sum(len(r) for r in raws)
        if total < self.config.inline_object_max_bytes:
            self.memory_store.put_value(oid, value, size=total)
        else:
            if self.client_mode:
                # remote client: this host's shm is invisible to the cluster;
                # stream the packed bytes to the head's store instead
                shm_name, size = self._client_upload(oid, data, raws)
            else:
                shm_name, size = self.shm_store.create_and_pack(oid, data, raws)
            self.memory_store.put_shm(oid, shm_name, size)
            if nested:
                self._promote_nested(nested)
            if not self.client_mode:
                self._notify_threadsafe(
                    "obj_created", oid=oid.binary(), shm_name=shm_name, size=size
                )
                if self.owner_ledger is not None:
                    # the ledger serves owner_pin/owner_locate from this even
                    # after the local read-cache entry is evicted
                    self.owner_ledger.set_location(oid.binary(), shm_name, size)
            if nested:
                # borrowed refs inside the stored value live as long as the
                # containing object (containment edges at each inner object's
                # authority; head-resident when the plane is off)
                self._register_contains(oid.binary(), nested)

    def _client_upload(self, oid: ObjectID, data: bytes, raws: List[Any]) -> Tuple[str, int]:
        """Client-mode put: chunk the packed bytes to the head, which hosts
        them in its n0 namespace and registers this client as owner."""
        from .serialization import pack_chunks_from_parts

        total, chunks = pack_chunks_from_parts(data, raws)
        return self._client_upload_chunks(oid, total, chunks)

    def _client_upload_blob(self, oid: ObjectID, blob: bytes) -> Tuple[str, int]:
        """Upload an already pack()-framed blob verbatim (client mode)."""
        return self._client_upload_chunks(oid, len(blob), [blob])

    def _client_upload_chunks(self, oid: ObjectID, total: int, chunks) -> Tuple[str, int]:
        return self.run_coro(self._client_upload_chunks_async(oid, total, chunks))

    def _upload_packets(self, chunks, limit: int):
        """Yield (off, bytes) packets straight off each chunk's memory: no
        concat buffer, no O(N^2) drain — one bytes() copy per packet
        (msgpack needs it) is the only extra traffic."""
        off = 0
        for c in chunks:
            mv = memoryview(c)
            if mv.ndim != 1 or mv.itemsize != 1:
                mv = mv.cast("B")
            pos = 0
            while pos < len(mv):
                n = min(limit, len(mv) - pos)
                yield off, bytes(mv[pos : pos + n])
                off += n
                pos += n

    async def _client_upload_chunks_async(
        self, oid: ObjectID, total: int, chunks
    ) -> Tuple[str, int]:
        """Client-mode put upload with the transfer window applied: up to
        config.transfer_window client_put_chunk RPCs stay in flight (each
        packet carries its offset, so completion order is irrelevant —
        the head writes them into the mmap'd segment out of order)."""
        oid_b = oid.binary()
        await self.head.call("client_put_begin", oid=oid_b, size=total)
        limit = self.config.transfer_chunk_bytes
        window = max(1, int(getattr(self.config, "transfer_window", 4)))
        inflight: set = set()
        try:
            for off, data in self._upload_packets(chunks, limit):
                while len(inflight) >= window:
                    done, inflight = await asyncio.wait(
                        inflight, return_when=asyncio.FIRST_COMPLETED
                    )
                    err = None
                    for d in done:
                        # consume EVERY done task's exception (several sends
                        # can fail in one wait — leaving any unretrieved
                        # logs 'Task exception was never retrieved'), then
                        # surface the first
                        e = None if d.cancelled() else d.exception()
                        err = err or e
                    if err is not None:
                        raise err
                inflight.add(
                    asyncio.ensure_future(
                        self.head.call(
                            "client_put_chunk", oid=oid_b, off=off, data=data
                        )
                    )
                )
                TRANSFER_STATS["bytes_uploaded"] += len(data)
            if inflight:
                await asyncio.gather(*inflight)
                inflight = set()
        except BaseException:
            for f in inflight:
                if not f.done():
                    f.cancel()
                elif not f.cancelled():
                    f.exception()  # consumed: no never-retrieved warnings
            raise
        r = await self.head.call("client_put_seal", oid=oid_b)
        return r["name"], total

    async def _promote_nested_async(self, nested: List[bytes], depth: int = 0):
        """Loop-thread-safe promotion for client mode: uploads await the
        head directly instead of blocking head_call (which cannot run on
        the IO loop).  Non-client promotion is local and needs no await."""
        if not self.client_mode:
            self._promote_nested(nested, depth)
            return
        if depth > 5:
            return
        for oid_b in nested:
            oid = ObjectID(oid_b)
            e = self.memory_store.get_entry(oid)
            if e is None or e.shm_name is not None or e.state not in ("value", "packed"):
                continue
            try:
                if e.state == "packed":
                    sub: List[bytes] = []
                    name, size = await self._client_upload_chunks_async(
                        oid, len(e.packed), [e.packed]
                    )
                else:
                    with serialization.ref_capture() as sub:
                        data, buffers = serialization.serialize(e.value)
                    from .serialization import pack_chunks_from_parts

                    total, chunks = pack_chunks_from_parts(
                        data, [b.raw() for b in buffers]
                    )
                    name, size = await self._client_upload_chunks_async(
                        oid, total, chunks
                    )
            except asyncio.CancelledError:
                raise
            except Exception:
                continue
            e.shm_name = name
            e.size = size
            if sub:
                await self._promote_nested_async(sub, depth + 1)
                self._notify_threadsafe("obj_contains", oid=oid_b, refs=list(sub))

    # ------------------------------------------------------------------ get
    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        oids = [r.id for r in ref_list]
        for r in ref_list:
            self._seed_borrowed(r.id, owner=r.owner)
        notified = False
        if self.mode == "worker" and not all(self.memory_store.contains(o) for o in oids):
            self._notify_blocked(True)
            notified = True
        try:
            ready, not_ready = self.memory_store.wait_ready(oids, len(oids), timeout)
            if not_ready:
                raise GetTimeoutError(f"get() timed out waiting for {len(not_ready)} objects")
            values = [self._resolve_entry(r) for r in ref_list]
        finally:
            if notified:
                self._notify_blocked(False)
        return values[0] if single else values

    def _notify_blocked(self, blocked: bool):
        def _send():
            if self.head and not self.head.closed:
                try:
                    self.head.notify(
                        "worker_blocked" if blocked else "worker_unblocked",
                        client_id=self.client_id,
                    )
                except Exception:
                    pass

        try:
            self.loop.call_soon_threadsafe(_send)
        except RuntimeError:
            pass

    def _seed_borrowed(self, oid: ObjectID, owner: Optional[str] = None):
        """A borrowed handle (deserialized from another process) has no local
        entry: seed one from the object directory so get()/wait() can resolve
        it.  Objects not yet created (ref to an unfinished task's return,
        forwarded ahead of completion) are polled until they appear.

        Ownership-based read path (future_resolver.h /
        ownership_based_object_directory.h): the poll goes to the OWNER
        process over a direct connection — its answer is authoritative for
        objects it created — so N borrowers polling M pending objects land
        on the owners, not on the head's single loop.  The head is consulted
        as a periodic fallback (owner dead, object spilled/relocated, owner
        not dialable)."""
        if self.memory_store.get_entry(oid) is not None:
            return
        self.memory_store.mark_pending(oid)
        oid_b = oid.binary()

        async def _poll():
            # no deadline: the object may belong to a task still running (ref
            # forwarded ahead of completion) — the caller's get() timeout
            # governs.  The poll ends when the entry fills, or when the local
            # handle is dropped (eviction deletes the entry).
            interval = 0.02
            owner_addr = await self._owner_addr_async(owner)
            owner_conn = None
            attempt = 0
            dead_strikes = 0
            first_strike_t = 0.0
            _now_mono = time.monotonic
            while True:
                e = self.memory_store.get_entry(oid)
                if e is None or e.state != "pending":
                    return  # filled or dropped meanwhile
                reply = {}
                asked_head = False
                if owner_addr is not None:
                    dialing = owner_conn is None or owner_conn.closed
                    try:
                        if dialing:
                            # bounded dial: an unreachable host must fail fast
                            # into the head fallback, not sit in the kernel
                            # SYN timeout with the every-8th head check stuck
                            # behind it.  shield: conn_to's in-flight future
                            # is shared per-addr — a bare wait_for would
                            # cancel-poison every other coroutine awaiting
                            # the same dial
                            owner_conn = await asyncio.wait_for(
                                asyncio.shield(self.conn_to(owner_addr)),
                                timeout=5,
                            )
                        reply = await owner_conn.call(
                            "owner_locate", oid=oid_b, timeout=10
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        owner_conn = None
                        if dialing:
                            # undialable: expire the session-long positive
                            # cache so resolutions re-ask the head instead of
                            # re-dialing a dead address
                            owner_addr = None
                            self._owner_addr_cache[owner] = (
                                None,
                                time.monotonic() + self._OWNER_ADDR_NEG_TTL,
                            )
                        # a mere call timeout (owner busy running the task)
                        # keeps the address: inline-only objects exist ONLY
                        # at the owner, so giving up on it for the rest of
                        # the poll could make them unresolvable
                elif attempt % 8 == 7:
                    # the owner path may have recovered (restarted head,
                    # momentary blip at first resolution): re-ask under the
                    # neg-TTL cache, which bounds head traffic
                    owner_addr = await self._owner_addr_async(owner)
                # every 8th attempt (and always without an owner), check the
                # head too — it alone knows spill relocations and survives
                # owner death
                if not reply.get("found") and (
                    owner_addr is None or attempt % 8 == 7
                ):
                    asked_head = True
                    try:
                        reply = await self.head.call("obj_locate", oid=oid_b)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        reply = {}
                    if (
                        not reply.get("found")
                        and owner
                        and owner_addr is None
                        and attempt % 8 == 7
                    ):
                        # OwnerDiedError role: the head has no copy AND the
                        # owner's client record is tombstoned — the object's
                        # only authority is gone, so fail fast instead of
                        # polling to the caller's timeout.  Probed at the
                        # same every-8th cadence as owner re-resolution (no
                        # per-attempt head RPC), and requiring TWO strikes
                        # >= 3s apart: a restarting head briefly marks live
                        # workers dead before re-adoption, and a transient
                        # disconnect of a live client-mode driver tombstones
                        # it until its housekeeping reconnect — neither
                        # window may condemn the object.
                        try:
                            cr = await self.head.call(
                                "client_addr", client_id=owner
                            )
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            cr = {}
                        if cr.get("dead"):
                            if dead_strikes == 0:
                                first_strike_t = _now_mono()
                            dead_strikes += 1
                        else:
                            dead_strikes = 0
                        if dead_strikes >= 2 and _now_mono() - first_strike_t >= 3.0:
                            e2 = self.memory_store.get_entry(oid)
                            if e2 is not None and e2.state == "pending":
                                self.memory_store.put_error(
                                    oid,
                                    ObjectLostError(
                                        f"object {oid} is unrecoverable: its "
                                        f"owner ({owner}) died and no other "
                                        "copy or lineage is known to the head"
                                    ),
                                )
                            return
                if reply.get("found"):
                    if reply.get("v") is not None:
                        # inline payload served straight from the owner; seed
                        # ack routing first — an unpack failure must still
                        # release the pin at the ledger that holds it
                        self._note_transit_owners(reply)
                        try:
                            value = serialization.unpack(reply["v"])
                        except Exception:
                            if reply.get("t"):
                                # we can't consume it: release the owner's
                                # transit pin without claiming holdership, or
                                # every retry tick leaks another pin
                                self.transit_done(
                                    reply["t"], reply.get("roids") or [],
                                    register=False,
                                )
                            reply = {}  # corrupt/unreadable: keep polling
                        else:
                            self.memory_store.put_value(oid, value)
                            if reply.get("t"):
                                # our handles for smuggled nested refs are
                                # registered by unpack: release the owner's
                                # transit pin (borrowing protocol)
                                self.transit_done(
                                    reply["t"], reply.get("roids") or []
                                )
                            return
                    else:
                        self.memory_store.put_shm(
                            oid, reply["shm_name"], reply["size"]
                        )
                        return
                attempt += 1
                await asyncio.sleep(interval)
                # owner polls back off to a low cap (direct and distributed,
                # but the owner's IO loop is also running the producing task);
                # head-only polls back off further to protect the shared loop
                cap = 1.0 if (owner_addr is None or asked_head) else 0.2
                interval = min(interval * 2, cap)

        try:
            self.loop.call_soon_threadsafe(lambda: spawn_bg(_poll()))
        except RuntimeError:
            pass

    def _evict_on_zero(self, oid: ObjectID):
        e = self.memory_store.get_entry(oid)
        if e is None:
            return
        # Always safe to drop the local entry at local-zero:
        #  - shm-backed / borrowed: the head (cluster refcount) owns lifetime;
        #    this is just a read-cache eviction.
        #  - owned, never promoted to shm: inline-only objects are invisible
        #    to every other process (escaping refs get promoted by
        #    _promote_nested), so nothing can ever resolve this oid again —
        #    retaining it leaked one entry per completed task.
        self.memory_store.delete(oid)
        if self.reference_counter.is_owned(oid):
            self.reference_counter.remove_owned(oid)
            self.device_objects.pop(oid.binary(), None)
            # lineage is only useful while some ref could still ask for
            # reconstruction: when every return object of the producing task
            # has dropped to zero local refs, the task spec can go too
            # (otherwise the table pins 8k specs of long-dead tasks)
            if not oid.is_put():
                rec = self._lineage.get(oid.task_id().binary())
                if rec is not None:
                    dead = rec.setdefault("dead", set())
                    dead.add(oid)
                    if len(dead) >= len(rec["oids"]):
                        self._lineage.pop(oid.task_id().binary(), None)

    def lineage_revive(self, oid: ObjectID):
        """A new local handle appeared for `oid` (count 0 -> 1): un-mark it
        dead so its producing task's spec stays reconstruction-eligible."""
        if oid.is_put():
            return
        rec = self._lineage.get(oid.task_id().binary())
        if rec is not None:
            d = rec.get("dead")
            if d is not None:
                d.discard(oid)

    def _make_value_pin(self, oid: ObjectID):
        """Register a value-holder for an arena-backed object and return the
        callback that releases it (runs from GC in any thread).  Pin and
        unpin ride the debounced obj_refs coalescer: a flood of zero-copy
        reads costs a handful of logical messages, not one per object.  The
        unpin captures the owner at pin time — a view can outlive both the
        handle and the borrowed-owner map entry, and its release must still
        reach the ledger that holds the pin."""
        pin_id = f"{self.client_id}#v"
        oid_b = oid.binary()
        owner = self._borrowed_owners.get(oid_b)
        self._queue_refs([oid_b], [], as_id=pin_id)

        def _unpin():
            if owner is not None:
                self._borrowed_owners.setdefault(oid_b, owner)
            self._queue_refs([], [oid_b], as_id=pin_id)

        return _unpin

    def _resolve_entry(self, ref: ObjectRef) -> Any:
        """Resolve an ObjectRef to its value; a lost object (node death,
        producer crash) is transparently recomputed from lineage by
        re-executing its creating task (ObjectRecoveryManager analogue),
        recursively for lost dependencies."""
        try:
            return self._resolve_entry_once(ref)
        except (ObjectLostError, FileNotFoundError) as err:
            if not self._reconstruct_object(ref.id):
                if isinstance(err, ObjectLostError):
                    raise
                raise ObjectLostError(f"object {ref.id} lost: {err}") from err
            return self._resolve_entry_once(ref)

    def _resolve_entry_once(self, ref: ObjectRef) -> Any:
        e = self.memory_store.get_entry(ref.id)
        if e is None:
            raise ObjectLostError(f"object {ref.id} unknown")
        if e.state == "value":
            return e.value
        if e.state == "error":
            raise e.error
        if e.state == "packed":
            value = serialization.unpack(e.packed)
            self.memory_store.put_value(ref.id, value, size=e.size)
            return value
        if e.state == "shm":
            return self._read_shm_entry(ref, e)
        if e.state == "device":
            # device value owned by another process: explicit materialization
            return self._fetch_remote(ref, e)
        raise ObjectLostError(f"object {ref.id} in unexpected state {e.state}")

    def _on_io_thread(self) -> bool:
        try:
            asyncio.get_running_loop()
            return True
        except RuntimeError:
            return False

    def _pin_unref_cb(self, oid_b: bytes):
        pin_id = f"{self.client_id}#v"
        # capture the pin's authority: the unpin may fire from GC after the
        # borrowed-owner map entry was pruned (see _make_value_pin)
        owner = self._borrowed_owners.get(oid_b)

        def _unpin():
            if owner is not None:
                self._borrowed_owners.setdefault(oid_b, owner)
            self._queue_refs([], [oid_b], as_id=pin_id)

        return _unpin

    def _owner_pin_blocking(self, oid_b: bytes) -> Optional[dict]:
        """Confirmed zero-copy pin at the object's OWNER (the head-free read
        path of the ownership plane): our own ledger when we own it, an
        owner_pin RPC otherwise.  None = no authoritative answer (owner
        unknown/unreachable, entry gone) — the caller falls back to the
        head, which arbitrates for adopted/centralized objects."""
        if not self._owner_plane:
            return None
        pin_id = f"{self.client_id}#v"
        led = self.owner_ledger
        if led is not None and led.tracks(oid_b):
            # led.pin counts pins_served itself (shared with the RPC path)
            loc = led.pin(oid_b, pin_id)
            if loc is None:
                return None
            return {"found": True, "node": self.node_id, **loc}
        owner = self._borrowed_owners.get(oid_b)
        if not owner:
            return None
        addr = self._owner_addr(owner)
        if not addr:
            return None

        async def _pin():
            conn = await self.conn_to(addr)
            return await conn.call("owner_pin", oid=oid_b, as_id=pin_id, timeout=10)

        try:
            r = self.run_coro(_pin(), timeout=15)
        except Exception:
            return None
        return r if r.get("found") else None

    def _read_shm_entry(self, ref: ObjectRef, e: _Entry) -> Any:
        """Materialize a shm-backed entry: confirmed pin + authoritative
        location from the head (atomic, so spilling can never recycle a slice
        under the mapping), node-to-node pull when remote, disk read when
        spilled, and relocation retry on stale slices."""
        oid_b = ref.id.binary()
        on_loop = self._on_io_thread()
        last_err: Optional[BaseException] = None
        for _ in range(3):
            name = e.shm_name
            pin_cb = None
            loc = None
            if on_loop:
                # rare loop-thread resolution (serving fetch_object): the
                # notify-based pin accepts a tiny pin-vs-spill race
                if "@" in name:
                    pin_cb = self._make_value_pin(ref.id)
            else:
                loc = self._owner_pin_blocking(oid_b)
                if loc is None:
                    loc = self.head_call(
                        "obj_pin", oid=oid_b, as_id=f"{self.client_id}#v"
                    )
                if not loc.get("found"):
                    # obj_created may still be in flight on the producer's
                    # socket while our entry (from the task reply) is already
                    # readable locally: read it directly — spilling cannot
                    # touch an unregistered object.  The notify-style pin
                    # lands in the head's early-refs buffer.
                    if name and self.shm_store.is_local(name):
                        pin_cb = self._make_value_pin(ref.id) if "@" in name else None
                        value = serialization.unpack(
                            self.shm_store.open(name), pin_cb=pin_cb
                        )
                        e.value = value
                        e.state = "value"
                        return value
                    raise ObjectLostError(f"object {ref.id} not in the directory")
                pin_cb = self._pin_unref_cb(oid_b)
                if loc.get("spill_path"):
                    name = "spill:" + loc["spill_path"]
                elif loc.get("node") == self.node_id and loc.get("shm_name"):
                    name = loc["shm_name"]
            try:
                if not self.shm_store.is_local(name):
                    name, _ = self.run_coro(self._ensure_local_shm(oid_b, name, e.size))
                value = serialization.unpack(self.shm_store.open(name), pin_cb=pin_cb)
                if not name.startswith("spill:"):
                    e.shm_name = name
                e.value = value
                e.state = "value"
                return value
            except (StaleObjectError, FileNotFoundError) as err:
                last_err = err
                if pin_cb is not None:
                    pin_cb()  # release this attempt's pin before retrying
                continue  # re-pin for a fresh location
        raise ObjectLostError(f"object {ref.id} unreadable after relocation: {last_err}")

    def _fetch_remote(self, ref: ObjectRef, e: _Entry) -> Any:
        owner_addr = e.shm_name  # device entries store owner addr here
        reply = self.run_coro(self._fetch_remote_async(owner_addr, ref.id.binary()))
        from ..channel.device_transport import maybe_unpack

        value = maybe_unpack(serialization.unpack(reply["packed"]))
        self.memory_store.put_value(ref.id, value)
        return value

    async def _fetch_remote_async(self, addr: str, oid: bytes):
        conn = await self.conn_to(addr)
        return await conn.call("fetch_object", oid=oid, timeout=self.config.push_timeout_s)

    # ----------------------------------------------- node-to-node transfer
    async def _ensure_local_shm(self, oid_b: bytes, shm_name: Optional[str] = None, size: int = 0):
        """Make a shm object local to this node, pulling it in chunks from
        the node(s) holding live copies if necessary (the client side of
        the reference's ObjectManager pull protocol).  Returns (local
        shm_name, size).  Concurrent pulls of the same object share one
        transfer; a CANCELLED leader must not poison the surviving waiters
        — they inherit only the leader's real failures, and retry (becoming
        the new leader) when the shared future died of cancellation."""
        while True:
            if shm_name is not None and self.shm_store.is_local(shm_name):
                return shm_name, size
            fut = self._pulls.get(oid_b)
            if fut is None:
                break
            try:
                # shield: a waiter's own cancellation must not cancel the
                # SHARED future out from under every other waiter
                return await asyncio.shield(fut)
            except asyncio.CancelledError:
                if fut.cancelled() or (
                    fut.done()
                    and isinstance(fut.exception(), asyncio.CancelledError)
                ):
                    # the LEADER was cancelled (its getter timed out or its
                    # task died) — the transfer never completed and never
                    # really failed.  Loop: take over as the new leader.
                    continue
                raise  # WE were cancelled: propagate our own cancellation
        fut = asyncio.get_running_loop().create_future()
        self._pulls[oid_b] = fut
        try:
            result = await self._pull_object(oid_b)
            fut.set_result(result)
            return result
        except BaseException as e:
            fut.set_exception(e)
            # consume the exception if nobody else awaited the future
            if not fut.cancelled():
                fut.exception()
            raise
        finally:
            del self._pulls[oid_b]

    def _pull_sources(self, reply: dict) -> List[dict]:
        """Dialable holders for a located object: the directory's `sources`
        list (primary first, then secondary copies), de-duplicated, with a
        legacy single-source fallback for mixed-version heads.  With
        transfer_multi_source off only the primary is used."""
        srcs: List[dict] = []
        seen = set()
        for s in reply.get("sources") or ():
            addr, name = s.get("pull_addr"), s.get("shm_name")
            if addr and name and (addr, name) not in seen:
                seen.add((addr, name))
                srcs.append({"addr": addr, "shm_name": name})
        if not srcs:
            name = reply.get("shm_name")
            if reply.get("spill_path"):
                name = "spill:" + reply["spill_path"]
            if name and reply.get("pull_addr"):
                srcs.append({"addr": reply["pull_addr"], "shm_name": name})
        if not getattr(self.config, "transfer_multi_source", True):
            srcs = srcs[:1]
        return srcs

    async def _pull_object(self, oid_b: bytes):
        reply = await self.head.call("obj_locate", oid=oid_b)
        if not reply.get("found"):
            raise ObjectLostError(
                f"object {oid_b.hex()} not found in the cluster (node lost?)"
            )
        total = reply["size"]
        name = reply.get("shm_name")
        if reply.get("spill_path"):
            name = "spill:" + reply["spill_path"]
        if name is not None and self.shm_store.is_local(name):
            return name, total  # a copy (or local spill file) on this node
        if name is None and not reply.get("sources"):
            raise ObjectLostError(f"object {oid_b.hex()} has no readable location")
        oid = ObjectID(oid_b)
        local_name, mv = self.shm_store.create_for_import(oid, total)
        try:
            # cross-plane tracing: the pull is a span under whatever task
            # is waiting on it (no-op without an ambient trace)
            from ..util import tracing as _tracing

            with _tracing.span(f"transfer:pull:{oid_b.hex()[:8]}"):
                await self._pull_into(oid_b, mv, total, reply)
        except BaseException:
            mv.release()
            self.shm_store.abort_import(local_name)  # aborted pull: reclaim
            raise
        mv.release()
        self.shm_store.seal_done(local_name)
        self._notify_obj_copy(oid_b, local_name)
        return local_name, total

    async def _pull_into(self, oid_b: bytes, mv, total: int, reply: dict):
        """Windowed, multi-source chunk transfer into an import arena slice.

        Up to config.transfer_window pull_chunk RPCs stay in flight PER
        SOURCE (the reference ObjectManager's windowed pull discipline)
        instead of one serial request-response round-trip at a time, and
        completed chunks land out of order (each carries its offset).  When
        the directory reports several live copies, every holder's lanes
        drain one shared chunk queue, so the byte range splits across
        sources by throughput.  A failing source re-queues its in-flight
        chunk and drops out (failover, not fatal); when every source died
        with chunks left, the object is re-located and the pull resumes —
        only the missing chunks are re-fetched."""
        chunk = self.config.transfer_chunk_bytes
        window = max(1, int(getattr(self.config, "transfer_window", 4)))
        pending: deque = deque(
            (off, min(chunk, total - off)) for off in range(0, total, chunk)
        )
        inflight = 0
        peak = 0
        completed = 0
        served: set = set()  # sources that landed >= 1 chunk
        last_err: Optional[BaseException] = None

        async def _lane(src: dict) -> None:
            nonlocal inflight, peak, completed
            conn = await self.conn_to(src["addr"])
            while pending:
                off, ln = pending.popleft()
                inflight += 1
                peak = max(peak, inflight)
                try:
                    r = await conn.call(
                        "pull_chunk", shm_name=src["shm_name"], off=off,
                        len=ln, timeout=self.config.push_timeout_s,
                    )
                    data = r["data"]
                    if len(data) != ln:
                        # short read: size metadata disagrees with the
                        # served file — treat the source as bad
                        raise ObjectLostError(
                            f"short read pulling {oid_b.hex()}: got "
                            f"{len(data)} of {ln} bytes at {off}/{total}"
                        )
                except BaseException:
                    # the chunk is NOT lost: back on the queue for the
                    # surviving lanes/sources (or the next locate round)
                    pending.appendleft((off, ln))
                    raise
                finally:
                    inflight -= 1
                mv[off : off + ln] = data
                completed += 1
                TRANSFER_STATS["bytes_pulled"] += ln
                TRANSFER_STATS["chunks_pulled"] += 1
                served.add(src["addr"])

        async def _source(src: dict) -> None:
            nonlocal last_err
            lanes = min(window, max(1, len(pending)))
            results = await asyncio.gather(
                *(_lane(src) for _ in range(lanes)), return_exceptions=True
            )
            errs = [e for e in results if isinstance(e, BaseException)]
            for e in errs:
                if isinstance(e, asyncio.CancelledError):
                    raise e
            if errs:
                # the source dropped out and its re-queued chunks were (or
                # will be) re-assigned — failover, whether the survivors
                # already drained them or a re-locate round picks them up
                last_err = errs[0]
                TRANSFER_STATS["source_failovers"] += 1
                from ..util import flightrec

                if flightrec.REC is not None:
                    flightrec.REC.record(
                        "transfer", "source_failover", oid=oid_b.hex(),
                        source=src.get("addr"), error=repr(errs[0]),
                        chunks_left=len(pending),
                    )

        stalled = 0
        rounds = 0
        while pending:
            sources = self._pull_sources(reply)
            if not sources:
                raise ObjectLostError(
                    f"object {oid_b.hex()} is on node {reply.get('node')} "
                    f"with no reachable object server"
                ) from last_err
            before = completed
            # _source never raises except on cancellation, so a plain gather
            # is a barrier that propagates cancellation and nothing else
            await asyncio.gather(*(_source(s) for s in sources))
            if not pending:
                break
            rounds += 1
            stalled = stalled + 1 if completed == before else 0
            TRANSFER_STATS["pull_retry_rounds"] += 1
            if stalled >= 3 or rounds >= 16:
                raise ObjectLostError(
                    f"pull of {oid_b.hex()} failed after {rounds} rounds "
                    f"({len(pending)} chunks missing): {last_err!r}"
                ) from last_err
            await asyncio.sleep(0.2 * stalled)
            # every source died mid-transfer: ask the directory again — a
            # survivor copy / relocated spill can finish the remainder
            reply = await self.head.call("obj_locate", oid=oid_b)
            if not reply.get("found"):
                raise ObjectLostError(
                    f"object {oid_b.hex()} lost mid-pull "
                    f"({len(pending)} chunks missing)"
                ) from last_err
        TRANSFER_STATS["pulls"] += 1
        TRANSFER_STATS["window_peak_sum"] += peak if peak else 1
        TRANSFER_STATS["sources_used"] += len(served)
        if len(served) > 1:
            TRANSFER_STATS["multi_source_pulls"] += 1

    def _notify_obj_copy(self, oid_b: bytes, local_name: str) -> None:
        """Record the freshly pulled copy in the head's directory so later
        pulls can multi-source from this node.  A failed notify DEFERS for
        housekeeping re-send (the obj_release idiom) instead of being
        swallowed: losing it silently meant the head never learned about
        the copy — invisible to multi-source splitting and never reclaimed
        by name on eviction."""
        head = self.head
        if head is not None and not head.closed:
            try:
                head.notify(
                    "obj_copy", oid=oid_b, node=self.node_id,
                    shm_name=local_name,
                )
                return
            except Exception:
                pass
        TRANSFER_STATS["copy_notify_deferred"] += 1
        self._deferred_copy_notifies.append((oid_b, local_name))

    def ensure_local_shm_blocking(self, oid_b: bytes, shm_name: str, size: int = 0) -> str:
        """Thread-safe blocking wrapper (used by executor threads resolving
        task args that reference another node's objects)."""
        name, _ = self.run_coro(self._ensure_local_shm(oid_b, shm_name, size))
        return name

    # ------------------------------------------------- lineage reconstruction
    def _object_available(self, oid: ObjectID) -> bool:
        """Is the object's data still reachable (locally or in the cluster)?"""
        e = self.memory_store.get_entry(oid)
        if e is None or e.state == "error":
            return False
        if e.state == "shm":
            try:
                reply = self.head_call("obj_locate", oid=oid.binary())
            except Exception:
                return False
            return bool(reply.get("found"))
        return True  # value/packed/pending/device resolved in-process

    def _reconstruct_object(self, oid: ObjectID, depth: int = 0) -> bool:
        """Recompute a lost object by re-executing its creating task
        (lineage-based recovery, object_recovery_manager.h:38).  Blocking;
        must run on a user thread (it drives RPCs through the IO loop).
        Returns True when the object's entries were refilled."""
        try:
            asyncio.get_running_loop()
            return False  # on the IO thread: cannot block on reconstruction
        except RuntimeError:
            pass
        if depth > 20 or oid.is_put():
            return False
        tid = oid.task_id().binary()
        rec = self._lineage.get(tid)
        if rec is None:
            return False
        # single-flight per creating task: concurrent getters of its returns
        # share one re-execution
        with self._recon_lock:
            ev = self._recon_events.get(tid)
            leader = ev is None
            if leader:
                ev = self._recon_events[tid] = threading.Event()
        if not leader:
            ev.wait(self.config.push_timeout_s)
            e = self.memory_store.get_entry(oid)
            return e is not None and e.state not in ("pending", "error")
        try:
            if rec["budget"] <= 0:
                return False
            rec["budget"] -= 1
            # dependencies first: a lost arg is recomputed recursively
            deps = list(rec["args"]) + list(rec["kwargs"].values())
            for a in deps:
                if isinstance(a, ObjectRef) and not self._object_available(a.id):
                    if not self._reconstruct_object(a.id, depth + 1):
                        return False
            oids = rec["oids"]
            reset = []
            for o in oids:
                # only resurrect siblings somebody can still read — a dead
                # sibling refilled here would pin an unevictable entry (and
                # _store_results would refuse to fill it, so waiting on it
                # below would stall the full push timeout)
                if (
                    o == oid
                    or self.memory_store.get_entry(o) is not None
                    or self.reference_counter.local_count(o) > 0
                ):
                    self.memory_store.reset_pending(o)
                    reset.append(o)
            task_id = TaskID(tid)
            self._pump_submit(
                lambda: self._task_entry(
                    task_id, rec["fn_id"], None, rec["args"], rec["kwargs"],
                    rec["opts"], oids,
                )
            )
            ready, not_ready = self.memory_store.wait_ready(
                reset, len(reset), self.config.push_timeout_s
            )
            return not not_ready
        finally:
            ev.set()
            with self._recon_lock:
                self._recon_events.pop(tid, None)

    # ------------------------------------------------------------------ wait
    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1, timeout: Optional[float] = None):
        ref_list = list(refs)
        if num_returns > len(ref_list):
            raise ValueError("num_returns exceeds number of refs")
        for r in ref_list:
            self._seed_borrowed(r.id, owner=r.owner)
        ready_ids, rest_ids = self.memory_store.wait_ready(
            [r.id for r in ref_list], num_returns, timeout
        )
        ready_set = set(ready_ids)
        ready, rest = [], []
        for r in ref_list:
            (ready if r.id in ready_set and len(ready) < num_returns else rest).append(r)
        return ready, rest

    def resolve_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _wait():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:
                fut.set_exception(e)

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    # ----------------------------------------------------------- arg packing
    def _notify_threadsafe(self, _method: str, **fields):
        """head.notify from any thread (the cork needs the running loop)."""
        def _send():
            if self.head is not None and not self.head.closed:
                try:
                    self.head.notify(_method, **fields)
                except Exception:
                    pass

        try:
            self.loop.call_soon_threadsafe(_send)
        except RuntimeError:
            pass

    # ------------------------------------------------------------- spilling
    def _spill_kick(self):
        """Non-blocking: wake (or start) the background spill thread — the
        IO-worker analogue of local_object_manager.h.  Called from the
        store's seal path when live bytes cross the high watermark, so the
        allocating put never waits on disk."""
        import queue as _queue

        with self._spill_start_lock:
            if self._spill_thread is None:
                self._spill_queue = _queue.Queue(maxsize=2)
                self._spill_thread = threading.Thread(
                    target=self._spill_loop, name="ca-spill", daemon=True
                )
                self._spill_thread.start()
        try:
            self._spill_queue.put_nowait(1)
        except _queue.Full:
            pass  # a pass is already queued; it will see the latest usage

    def _spill_loop(self):
        import queue as _queue

        low_frac = 0.5  # spill down to this fraction of the budget
        while not self._stopped:
            try:
                self._spill_queue.get(timeout=0.5)
            except _queue.Empty:
                continue
            store = self.shm_store
            if not store.budget_bytes:
                continue
            need = store.live_bytes() - int(store.budget_bytes * low_frac)
            if need > 0:
                self.spill_stats["background"] += 1
                self._spill_pass(need)

    def _spill_bytes(self, need: int):
        """Hard-wall spill on the allocating path: an allocation could not
        fit the budget even after the watermark spiller's work.  Kept as the
        correctness backstop; the proactive path (_spill_kick) exists so
        this rarely runs."""
        try:
            asyncio.get_running_loop()
            return  # IO-loop context (pull imports): cannot block on RPCs
        except RuntimeError:
            pass
        self.spill_stats["inline"] += 1
        self._spill_pass(max(need, self.shm_store.budget_bytes // 8))

    def _spill_pass(self, target: int):
        """Move the oldest live slices of this process to disk until `target`
        bytes are freed (LocalObjectManager spill analogue).  The slice's
        OWNER arbitrates when it is this process (ownership plane: the
        free-now-vs-defer decision is one ledger transition, the head just
        learns `obj_spilled` asynchronously for its snapshot); the head
        arbitrates for slices backing other owners' objects and on the
        centralized path.  Either way a slice under zero-copy pins is
        relocated but its memory reclaim is deferred to the last pin drop.
        Serialized: concurrent inline + background passes would re-spill the
        same slices."""
        if (self.head is None or self.head.closed) and self.owner_ledger is None:
            return
        with self._spill_lock:
            self._spill_pass_locked(target)

    def _spill_pass_locked(self, target: int):
        spill_dir = os.path.join(self.session_dir, "spill", self.node_id)
        os.makedirs(spill_dir, exist_ok=True)
        freed = 0
        for name, size, oid_b in self.shm_store.live_slices_oldest_first():
            if freed >= target:
                break
            if name in self._spilled_pinned:
                # already relocated to disk; its memory comes back only when
                # the last zero-copy pin drops — re-spilling would just
                # rewrite the same file for nothing
                continue
            led = self.owner_ledger
            if (
                (self.head is None or self.head.closed)
                and not (led is not None and led.tracks(oid_b))
            ):
                # borrowed slice with no arbiter reachable: it can only stay
                # in memory — check BEFORE the file write, or a head outage
                # under pressure rewrites and deletes the same multi-MB
                # files every pass
                continue
            try:
                mv = self.shm_store.open(name)
            except Exception:
                continue
            path = os.path.join(spill_dir, f"{oid_b.hex()}.bin")
            try:
                with open(path, "wb") as f:
                    f.write(mv)
            except OSError:
                mv.release()
                return  # disk full: stop spilling
            finally:
                try:
                    mv.release()
                except Exception:
                    pass
            led = self.owner_ledger
            if led is not None and led.tracks(oid_b):
                # owner-side decision: one ledger transition, no head RPC on
                # the allocating path (works with the head down, too)
                pinned = led.spill_transition(oid_b, path)
                if pinned is None:
                    # GC won the race: drop the file, reclaim the slice
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    self.shm_store.free_local(name)
                    freed += size
                    continue
                # the registry learns asynchronously (snapshot/pull routing)
                # (no `freed` field: the head never read it — the owner's
                # ledger is the pin authority, the registry only needs the
                # path; ca lint rpc-unread-field)
                self._notify_threadsafe(
                    "obj_spilled", oid=oid_b, path=path, size=size,
                    decided=True,
                )
                if pinned:
                    # memory comes back on the last value-pin drop
                    # (_ledger_pin_zero); never a spill candidate again
                    self._spilled_pinned.add(name)
                else:
                    self.shm_store.free_local(name)
                    freed += size
                continue
            if self.head is None or self.head.closed:
                # borrowed slice, no arbiter reachable: leave it in memory —
                # but keep scanning: later candidates may be OWNED slices
                # this process can settle head-free (spill_transition above)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            try:
                reply = self.head_call("obj_spilled", oid=oid_b, path=path, size=size)
            except Exception:
                # head died mid-pass: same story — owned candidates later in
                # the scan still settle without it
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if not reply.get("found"):
                # object already GC'd: drop the file, reclaim the slice
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self.shm_store.free_local(name)
                freed += size
            elif reply.get("free_now"):
                self.shm_store.free_local(name)
                freed += size
            else:
                # pinned: relocated but memory comes back later (pin drop);
                # never pick it as a spill candidate again
                self._spilled_pinned.add(name)

    def _promote_nested(self, nested: List[bytes], depth: int = 0):
        """Nested refs to inline-only objects have no cluster-visible data
        (inline values never register at the head): spill them to shm and
        register, so a borrower on any process/node can locate and read them.
        Thread-safe; recurses for refs nested inside the promoted values."""
        if depth > 5:
            return
        for oid_b in nested:
            oid = ObjectID(oid_b)
            e = self.memory_store.get_entry(oid)
            if e is None or e.shm_name is not None or e.state not in ("value", "packed"):
                continue
            try:
                if e.state == "packed":
                    sub: List[bytes] = []
                    if self.client_mode:
                        # already pack()-framed: upload the blob verbatim
                        name, size = self._client_upload_blob(oid, e.packed)
                    else:
                        name, mv = self.shm_store.create_for_import(
                            oid, len(e.packed), primary=True
                        )
                        try:
                            mv[:] = e.packed
                        except BaseException:
                            mv.release()
                            self.shm_store.abort_import(name)
                            raise
                        mv.release()
                        self.shm_store.seal_done(name)
                        size = len(e.packed)
                else:
                    with serialization.ref_capture() as sub:
                        data, buffers = serialization.serialize(e.value)
                    if self.client_mode:
                        name, size = self._client_upload(
                            oid, data, [b.raw() for b in buffers]
                        )
                    else:
                        name, size = self.shm_store.create_and_pack(
                            oid, data, [b.raw() for b in buffers]
                        )
            except Exception:
                continue
            e.shm_name = name
            e.size = size
            if not self.client_mode:
                self._notify_threadsafe(
                    "obj_created", oid=oid_b, shm_name=name, size=size, node=self.node_id
                )
                if self.owner_ledger is not None and self.owner_ledger.tracks(oid_b):
                    self.owner_ledger.set_location(oid_b, name, size)
            if sub:
                self._promote_nested(sub, depth + 1)
                self._register_contains(oid_b, list(sub))

    def transit_pin(self, nested: List[bytes]) -> str:
        """Pin in-transit borrowed refs at the head under a fresh token (the
        receiver releases it via transit_done).  Also promotes inline-only
        nested objects to shm so borrowers can actually fetch them."""
        self._promote_nested(nested)
        token = f"t:{self.client_id}:{self._put_counter.next()}"
        self._queue_refs(list(nested), [], as_id=token)
        return token

    def transit_owners(self, nested: List[bytes]) -> List[str]:
        """Per-roid authority metadata ("rown") shipped alongside a transit
        envelope: the cid whose ledger the sender's pin lands at ("" = the
        head).  The receiver seeds its routing from this BEFORE unpacking,
        so an ack for a payload that never unpacks still reaches the ledger
        holding the pin instead of tombstoning the token at the head."""
        if not self._owner_plane:
            return ["" for _ in nested]
        out = []
        for oid in nested:
            d = self._ref_dest(oid)
            out.append(self.client_id if d == "" else (d or ""))
        return out

    def _note_transit_owners(self, env: dict) -> None:
        """Seed borrowed-owner routing from a transit envelope's rown
        metadata (see transit_owners) so transit_done — and any later dec —
        routes to the authority the sender actually pinned at, even when
        the payload fails to unpack and no ObjectRef ever rehydrates."""
        owners = env.get("rown")
        if not owners or not self._owner_plane:
            return
        for oid, owner in zip(env.get("roids") or (), owners):
            if owner and owner != self.client_id:
                self._borrowed_owners.setdefault(bytes(oid), owner)

    def transit_done(self, token: str, roids: List[bytes],
                     register: bool = True) -> None:
        """Receiver-side ack: register this process as holder of the smuggled
        refs and release the sender's transit pin (thread-safe).
        register=False releases the pin without claiming holdership — for
        payloads the receiver failed to unpack.

        Routed per-oid to each object's lifetime authority (the pin was
        registered there by the sender's transit_pin): our own ledger, the
        owner's ledger over a direct connection, or the head fallback."""
        def _send():
            if not self._owner_plane:
                self._transit_done_head(token, roids, register)
                return
            groups: Dict[Optional[str], List[bytes]] = {}
            for oid in roids:
                groups.setdefault(self._ref_dest(oid), []).append(oid)
            for dest, oids in groups.items():
                if dest == "":
                    self.owner_ledger.transit_done(
                        token, oids, self.client_id, register
                    )
                elif dest is None:
                    self._transit_done_head(token, oids, register)
                else:
                    t = spawn_bg(
                        self._owner_transit_done_async(dest, token, oids, register)
                    )
                    t.add_done_callback(self._report_task_exc)

        try:
            self.loop.call_soon_threadsafe(_send)
        except RuntimeError:
            pass

    def _transit_done_head(self, token, oids, register) -> None:
        if self.head is not None and not self.head.closed:
            try:
                self.head.notify(
                    "transit_done", token=token, oids=oids, register=register
                )
            except Exception:
                pass

    async def _owner_transit_done_async(self, owner, token, oids, register) -> None:
        try:
            addr = await self._owner_addr_async(owner)
            if addr is None:
                raise ConnectionError(f"owner {owner} not dialable")
            conn = await self.conn_to(addr)
            conn.notify(
                "owner_transit_done", token=token, oids=oids,
                cid=self.client_id, register=register,
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            # dead owner: the head adopted its ledger — settle there
            self._transit_done_head(token, oids, register)

    async def _pack_with_transit_async(self, value: Any, ttl_pin: bool = False) -> dict:
        """_pack_with_transit usable on the IO loop: client-mode promotion
        awaits the head instead of blocking head_call.

        ttl_pin=True marks the pin for the head's lost-ack TTL sweep — ONLY
        for protocols whose ack time is bounded (the owner_locate serve path,
        where the borrower acks on unpack or promptly re-polls).  Task-arg
        pins must NOT set it: a queued task's ack waits for execution, which
        lease contention can delay indefinitely; their cleanup is sender
        liveness (head disconnect sweep)."""
        with serialization.ref_capture() as nested:
            blob = serialization.pack(value)
        if not nested:
            return {"v": blob}
        await self._promote_nested_async(nested)
        token = f"t:{self.client_id}:{self._put_counter.next()}"
        self._queue_refs(list(nested), [], as_id=token, ttl=bool(ttl_pin))
        return {
            "v": blob, "t": token, "roids": nested,
            "rown": self.transit_owners(nested),
        }

    async def _build_arg(self, value: Any) -> dict:
        """Build the wire spec for one task argument."""
        if isinstance(value, ObjectRef):
            oid = value.id
            # dependency resolution: wait until the local entry is ready
            while True:
                e = self.memory_store.get_entry(oid)
                if e is None:
                    raise ObjectLostError(f"arg object {oid} unknown to this process")
                if e.state != "pending":
                    break
                await asyncio.sleep(0.002)
            if e.state == "error":
                raise e.error
            if e.state == "device":
                return {"dev": oid.binary(), "owner": e.shm_name, "spec": e.value}
            if e.shm_name and e.state in ("shm", "value"):
                # keep shm provenance even after a local zero-copy read
                return {"shm": e.shm_name, "size": e.size, "oid": oid.binary()}
            if oid.binary() in self.device_objects:
                if not self.serve_addr:
                    # driver has no serving socket: ship inline, but as a
                    # sharding-preserving shard envelope, not a host copy
                    from ..channel.device_transport import pack_device_value

                    return {
                        "v": serialization.pack(
                            pack_device_value(self.device_objects[oid.binary()])
                        )
                    }
                return {
                    "dev": oid.binary(),
                    "owner": self.serve_addr,
                    "spec": _device_spec(self.device_objects[oid.binary()]),
                }
            # small local value: inline (packed)
            if e.state == "packed":
                return {"v": e.packed}
            return await self._pack_with_transit_async(e.value)
        # plain value: device values stay on device when this process can
        # serve them (workers/actors); the driver ships a shard envelope.
        if _is_device_value(value):
            if not self.serve_addr:
                from ..channel.device_transport import pack_device_value

                return {"v": serialization.pack(pack_device_value(value))}
            ref = self.put(value)
            return {
                "dev": ref.id.binary(),
                "owner": self.serve_addr,
                "spec": _device_spec(value),
            }
        return await self._pack_with_transit_async(value)

    async def _build_args(self, args: Sequence[Any], kwargs: Dict[str, Any]):
        if not args and not kwargs:
            return [], {}
        specs = [await self._build_arg(a) for a in args]
        kwspecs = {k: await self._build_arg(v) for k, v in kwargs.items()}
        return specs, kwspecs

    def _prepare_runtime_env(self, runtime_env: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Package a runtime_env into wire form, cached per env spec so a
        working_dir is zipped+uploaded once, not per task. (Packaging does
        blocking head RPCs: only call from user threads, never the IO loop.
        Caveat: edits to a working_dir after first use are not re-uploaded
        within one driver session — matches the reference's upload-once URIs.)
        """
        import json as _json

        from . import runtime_env as _re

        key = _json.dumps(runtime_env, sort_keys=True, default=repr)
        if not hasattr(self, "_runtime_env_cache"):
            self._runtime_env_cache = {}
        if key not in self._runtime_env_cache:
            self._runtime_env_cache[key] = _re.prepare(runtime_env, self)
        return self._runtime_env_cache[key]

    # ---------------------------------------------------------- task submit
    def submit_task(self, fn, args, kwargs, opts: Dict[str, Any]) -> List[ObjectRef]:
        if opts.get("runtime_env"):
            opts = dict(opts)
            opts["runtime_env"] = self._prepare_runtime_env(opts["runtime_env"])
        num_returns = opts.get("num_returns", 1)
        task_id = TaskID.for_normal_task(self.job_id)
        if TRACE_HOOK is not None:
            _tr = TRACE_HOOK.begin_task_trace(
                task_id.hex(), getattr(fn, "__name__", "task"), "task",
                self.client_id, self.node_id,
            )
            if _tr is not None:
                opts = dict(opts, _trace=_tr)
        oids = [ObjectID.for_return(task_id, i) for i in range(num_returns)]
        for oid in oids:
            self.memory_store.mark_pending(oid)
            self._add_owned(oid)
        refs = [ObjectRef(oid, owner=self.client_id, worker=self) for oid in oids]
        fn_id, blob = self.fn_manager.export(fn)
        self._record_lineage(task_id, fn_id, args, kwargs, opts, oids)
        self._pump_submit(
            lambda: self._task_entry(task_id, fn_id, blob, args, kwargs, opts, oids)
        )
        return refs

    def _record_lineage(self, task_id, fn_id, args, kwargs, opts, oids):
        budget = opts.get("max_retries", self.config.default_max_retries)
        if budget == 0:
            return  # max_retries=0 means not reconstructable either
        tid = task_id.binary()
        self._lineage[tid] = {
            "fn_id": fn_id,
            "args": args,
            "kwargs": kwargs,
            "opts": opts,
            "oids": oids,
            "budget": budget,
        }
        self._lineage_order.append(tid)
        while len(self._lineage_order) > self.config.lineage_cap:
            self._lineage.pop(self._lineage_order.popleft(), None)

    def _task_entry(self, task_id, fn_id, blob, args, kwargs, opts, oids):
        """Runs on the IO thread.  Fast path: an argless task of an
        already-exported function pushed onto an available lease entirely via
        callbacks — no per-task coroutine/Task.  When every lease is
        saturated, the task joins the pool's backlog (still no coroutine;
        release callbacks drain it).  Anything needing awaiting (arg
        resolution, function export) returns the slow coroutine instead."""
        if task_id.binary() in self._cancelled_tasks:
            self._store_error(oids, TaskCancelledError("task was cancelled"))
            return None
        if blob is not None or args or kwargs or opts.get("runtime_env"):
            return self._submit_task(task_id, fn_id, blob, args, kwargs, opts, oids)
        pool = self._lease_pool(opts)
        lease = pool._pick()
        # count this task as demand BEFORE deciding (both predicates read
        # inflight_total); a busy lease is only used when pipelining is the
        # right regime, else the task backlogs until growth/release
        if (
            lease is None
            or (lease.inflight > 0 and not pool._pipeline_ok_for(pool.inflight_total + 1))
        ):
            pool.enqueue_fast(task_id, fn_id, opts, oids)
            return None
        pool.inflight_total += 1
        if not self._push_fast(pool, lease, task_id, fn_id, opts, oids):
            pool.inflight_total -= 1
            return self._submit_task(task_id, fn_id, None, args, kwargs, opts, oids)
        return None

    def _push_fast(self, pool, lease, task_id, fn_id, opts, oids) -> bool:
        """Push one argless task onto `lease` purely via callbacks.  Returns
        False (without touching counters) if the connection is unusable —
        the caller decides the fallback.  On success the reply callback
        releases the lease and stores results/errors, retrying worker death
        within the task's budget."""
        addr = self._normalize_peer_addr(lease.addr)
        conn = self._conns.get(addr)
        if conn is None or conn.closed:
            return False
        lease.inflight += 1
        self._inflight_tasks[task_id.binary()] = addr

        def on_reply(msg):
            self._inflight_tasks.pop(task_id.binary(), None)
            pool.release(lease, dead=msg is None)
            if msg is None:
                if task_id.binary() in self._cancelled_tasks:
                    # force-cancel killed the worker mid-task: cancelled, not
                    # crashed, and never retried
                    self._store_error(oids, TaskCancelledError("task was cancelled"))
                    return
                # worker died with the push in flight: retry on a fresh lease
                # only within the task's retry budget (at-most-once otherwise).
                # Death on a DRAINING node is a preemption, not an app
                # failure: the retry is free — the budget is not touched
                retries = opts.get("max_retries", self.config.default_max_retries)
                if self._retry_exempt(lease.node):
                    DRAIN_STATS["tasks_evacuated_total"] += 1
                    t = spawn_bg(
                        self._submit_task(task_id, fn_id, None, (), {}, opts, oids)
                    )
                    t.add_done_callback(self._report_task_exc)
                elif retries > 0:
                    retry_opts = dict(opts, max_retries=retries - 1)
                    t = spawn_bg(
                        self._submit_task(task_id, fn_id, None, (), {}, retry_opts, oids)
                    )
                    t.add_done_callback(self._report_task_exc)
                else:
                    self._store_error(
                        oids, WorkerCrashedError("worker died executing task")
                    )
            elif not msg.get("ok", True):
                import pickle

                self._store_error(oids, pickle.loads(msg["err"]))
            else:
                self._store_results(oids, msg["results"], addr)

        trace = opts.get("_trace")
        num_returns = opts.get("num_returns", 1)
        retriable = opts.get("max_retries", self.config.default_max_retries) > 0
        # head down (restart window): inline the function definition — the
        # lease plane keeps granting, so a push must not strand its worker
        # on a head blob fetch it cannot make (once per conn+fn)
        fn_blob = self._fn_blob_for_push(conn, fn_id)

        def spec_fields():
            # one definition for both the template constants and the traced
            # full-encode path — they must never drift apart
            return {
                "m": "push_task",
                "fn_id": fn_id,
                "owner": self.client_id,
                "args": [],
                "kwargs": {},
                "num_returns": num_returns,
                "retriable": retriable,
            }

        try:
            if trace is None and fn_blob is None:
                tmpl = self._task_spec_template(
                    ("task", fn_id, num_returns), spec_fields, retriable=retriable
                )
                conn.call_template("push_task", tmpl, on_reply, task_id.binary())
            else:
                # traced or blob-inlined push: the pre-encoded template
                # cannot carry a per-call field, so the spec is encoded in
                # full, riding the same corked envelope
                if trace is not None and TRACE_HOOK is not None:
                    TRACE_HOOK.record_task_event(
                        task_id.hex(), None, "task", "SCHEDULED", trace=trace,
                        worker_id=self.client_id, node_id=self.node_id,
                        target=lease.worker_id,
                    )
                fields = spec_fields()
                del fields["m"]  # call_cb supplies the method
                if fn_blob is not None:
                    fields["fn_blob"] = fn_blob
                if trace is not None:
                    fields[TRACE_FIELD] = trace
                conn.call_cb(
                    "push_task", on_reply,
                    task_id=task_id.binary(),
                    **fields,
                )
        except ConnectionError:
            self._inflight_tasks.pop(task_id.binary(), None)
            lease.inflight -= 1
            lease.dead = True
            return False
        return True

    def _task_spec_template(self, key: tuple, fields_fn, retriable: bool) -> MsgTemplate:
        """Cached pre-encoded spec for the argless fast paths: the constant
        fields (function descriptor / actor method, options) are msgpack'd
        once; per call only the request id and task id are encoded."""
        key = key + (retriable,)
        tmpl = self._spec_templates.get(key)
        if tmpl is None:
            if len(self._spec_templates) > 4096:
                self._spec_templates.clear()  # runaway-fn_id backstop
            tmpl = self._spec_templates[key] = MsgTemplate(
                fields_fn(), ("i", "task_id")
            )
        return tmpl

    def _shape_of(self, opts) -> Dict[str, float]:
        shape = dict(opts.get("resources") or {})
        shape["CPU"] = float(opts.get("num_cpus", 1))
        if opts.get("num_tpus"):
            shape["TPU"] = float(opts["num_tpus"])
        return {k: v for k, v in shape.items() if v}

    def _lease_pool(self, opts) -> LeasePool:
        shape = self._shape_of(opts)
        pg = None
        if opts.get("placement_group") is not None:
            pg = (opts["placement_group"], opts.get("placement_group_bundle_index", 0))
        strat = opts.get("strategy")
        # canonical JSON: NODE_LABEL strategies carry nested selector dicts,
        # which a tuple-of-items key cannot hash
        strat_key = json.dumps(strat, sort_keys=True) if strat else None
        key = (tuple(sorted(shape.items())), pg, strat_key)
        pool = self._lease_pools.get(key)
        if pool is None:
            pool = LeasePool(self, key, shape, pg, strat)
            self._lease_pools[key] = pool
        return pool

    async def _submit_task(self, task_id, fn_id, blob, args, kwargs, opts, oids):
        try:
            if blob is not None:
                await self.head.call("register_function", fn_id=fn_id, blob=blob)
                self.fn_manager.mark_exported(fn_id)
            specs, kwspecs = await self._build_args(args, kwargs)
        except asyncio.CancelledError:
            # unblock get() waiters, then stay cancelled (a swallowed cancel
            # here would wedge worker shutdown mid-submission)
            self._store_error(oids, TaskCancelledError("submission cancelled"))
            raise
        except BaseException as e:
            self._store_error(oids, e)
            return
        retries = opts.get("max_retries", self.config.default_max_retries)
        pool = self._lease_pool(opts)
        trace = opts.get("_trace")
        if trace is not None and TRACE_HOOK is not None:
            TRACE_HOOK.record_task_event(
                task_id.hex(), None, "task", "QUEUED", trace=trace,
                worker_id=self.client_id, node_id=self.node_id,
            )
        while True:
            try:
                lease = await pool.acquire()
            except asyncio.CancelledError:
                self._store_error(oids, TaskCancelledError("submission cancelled"))
                raise
            except BaseException as e:
                self._store_error(oids, e)
                return
            if task_id.binary() in self._cancelled_tasks:
                # cancelled while waiting for a lease: never push
                pool.release(lease)
                self._store_error(oids, TaskCancelledError("task was cancelled"))
                return
            dead = False
            self._inflight_tasks[task_id.binary()] = self._normalize_peer_addr(
                lease.addr
            )
            try:
                conn = await self.conn_to(lease.addr)
                if trace is not None and TRACE_HOOK is not None:
                    TRACE_HOOK.record_task_event(
                        task_id.hex(), None, "task", "SCHEDULED", trace=trace,
                        worker_id=self.client_id, node_id=self.node_id,
                        target=lease.worker_id,
                    )
                # head down: inline the function definition (see _push_fast)
                extra = {}
                fn_blob = self._fn_blob_for_push(conn, fn_id)
                if fn_blob is not None:
                    extra["fn_blob"] = fn_blob
                if trace is not None:
                    extra[TRACE_FIELD] = trace
                # no RPC timeout here: the reply arrives only after the task
                # finishes, which may legitimately take arbitrarily long;
                # worker death is detected by the connection breaking.
                reply = await conn.call(
                    "push_task",
                    task_id=task_id.binary(),
                    fn_id=fn_id,
                    owner=self.client_id,
                    args=specs,
                    kwargs=kwspecs,
                    num_returns=opts.get("num_returns", 1),
                    runtime_env=opts.get("runtime_env"),
                    retriable=retries > 0,
                    timeout=None,
                    **extra,
                )
            except ConnectionError as e:
                dead = True
                if task_id.binary() in self._cancelled_tasks:
                    self._store_error(oids, TaskCancelledError("task was cancelled"))
                    return
                if self._retry_exempt(lease.node):
                    # preemption/drain kill: free retry, budget untouched
                    DRAIN_STATS["tasks_evacuated_total"] += 1
                    continue
                if retries > 0:
                    retries -= 1
                    continue
                self._store_error(
                    oids, WorkerCrashedError(f"worker died executing task: {e}")
                )
                return
            finally:
                self._inflight_tasks.pop(task_id.binary(), None)
                pool.release(lease, dead=dead)
            self._store_results(oids, reply["results"], lease.addr)
            return

    def _store_error(self, oids: List[ObjectID], e: BaseException):
        err = e if isinstance(e, CAError) else TaskError(repr(e))
        if oids:
            tid = oids[0].task_id().binary()
            if tid in self._cancelled_tasks and not isinstance(
                e, TaskCancelledError
            ):
                # the caller cancelled this task; whatever error the push
                # path surfaced afterwards (arg-resolution failure, backlog
                # drain) must not outrank the cancellation — a sibling ref's
                # get() may already have raised TaskCancelledError
                err = TaskCancelledError("task was cancelled")
            self._cancelled_tasks.discard(tid)
        for oid in oids:
            self.memory_store.put_error(oid, err)

    def _store_results(self, oids: List[ObjectID], results: List[dict], exec_addr: str):
        if oids:
            tid = oids[0].task_id().binary()
            if tid in self._cancelled_tasks:
                # the task outran its cancellation (value arrived anyway):
                # the caller asked for cancel semantics, and an earlier
                # get() may already have raised — stay consistent
                self._store_error(oids, TaskCancelledError("task was cancelled"))
                return
            self._cancelled_tasks.discard(tid)
        for oid, res in zip(oids, results):
            if "contains" in res:
                # owner-resident containment: the executing worker registered
                # the nested refs' edges; this (owner) ledger must remember —
                # or immediately release — them
                self._adopt_result_contains(oid.binary(), res)
            if (
                self.memory_store.get_entry(oid) is None
                and self.reference_counter.local_count(oid) == 0
                and oid.task_id().binary() not in self._streams
            ):
                # (stream items are exempt: they arrive before the consumer
                # creates a ref — the StreamState, not a ref count, keeps
                # them alive until read or the stream is abandoned)
                # fire-and-forget: every local handle died before the result
                # arrived (local-zero eviction already ran), so storing would
                # resurrect an entry nothing can ever read or evict again.
                # Smuggled refs still need their transit pin released: ack as
                # holder, then drop the holds we just acquired — but ONLY for
                # roids with no live local ref (holders is a set at the head,
                # so a dec here would erase a legitimate concurrent hold)
                if "t" in res:
                    self._note_transit_owners(res)
                    self.transit_done(res["t"], res["roids"])
                    dec = [
                        r
                        for r in res["roids"]
                        if self.reference_counter.local_count(ObjectID(r)) == 0
                    ]
                    if dec:
                        self._queue_refs([], dec)
                continue
            if "e" in res:
                import pickle

                self.memory_store.put_error(oid, pickle.loads(res["e"]))
            elif "v" in res:
                if "t" in res:
                    # inline value smuggling ObjectRefs: unpack eagerly so the
                    # rehydrated handles register before we release the
                    # sender's transit pin (lazy unpack would leave the
                    # nested refs unprotected once the sender drops its own).
                    # Seed ack routing first: the except path below never
                    # rehydrates, and its ack must still reach the pin
                    self._note_transit_owners(res)
                    try:
                        value = serialization.unpack(res["v"])
                    except Exception:
                        # undeserializable here (e.g. worker-only class): keep
                        # the refs safe by registering this process as holder
                        # anyway, and let the getter surface the real error
                        self.transit_done(res["t"], res["roids"])
                        self.memory_store.put_packed(oid, res["v"])
                    else:
                        self.memory_store.put_value(oid, value, size=len(res["v"]))
                        self.transit_done(res["t"], res["roids"])
                else:
                    self.memory_store.put_packed(oid, res["v"])
            elif "shm" in res:
                self.memory_store.put_shm(oid, res["shm"], res.get("size", 0))
                if self.owner_ledger is not None:
                    # this submitter owns the return: the ledger serves its
                    # location to borrowers even after local eviction
                    self.owner_ledger.set_location(
                        oid.binary(), res["shm"], res.get("size", 0)
                    )
            elif "dev" in res:
                e = _Entry("device", value=res.get("spec"), shm_name=res.get("owner", exec_addr))
                self.memory_store._store(oid, e)
            if self.reference_counter.local_count(oid) == 0 and not self.reference_counter.is_owned(oid):
                # the last handle died between the guard above and the store
                # (eviction already ran and found nothing): drop the entry we
                # just resurrected
                self.memory_store.delete(oid)

    # ------------------------------------------------------------- actors
    def create_actor(self, cls, args, kwargs, opts: Dict[str, Any]) -> Tuple[ActorID, str]:
        actor_id = ActorID.of(self.job_id)
        fn_id, blob = self.fn_manager.export(cls)
        wire_env = None
        if opts.get("runtime_env"):
            wire_env = self._prepare_runtime_env(opts["runtime_env"])  # user thread

        async def _create():
            if blob is not None:
                await self.head.call("register_function", fn_id=fn_id, blob=blob)
                self.fn_manager.mark_exported(fn_id)
            specs, kwspecs = await self._build_args(args, kwargs)
            init_spec = serialization.pack((specs, kwspecs))
            shape = dict(opts.get("resources") or {})
            if opts.get("num_cpus"):
                shape["CPU"] = float(opts["num_cpus"])
            if opts.get("num_tpus"):
                shape["TPU"] = float(opts["num_tpus"])
            reply = await self.head.call(
                "create_actor",
                actor_id=actor_id.hex(),
                name=opts.get("name"),
                fn_id=fn_id,
                init_spec=init_spec,
                resources=shape,
                max_restarts=opts.get("max_restarts", self.config.default_actor_max_restarts),
                detached=(opts.get("lifetime") == "detached"),
                max_concurrency=opts.get("max_concurrency", 1),
                concurrency_groups=opts.get("concurrency_groups"),
                method_options=opts.get("method_options"),
                pg_id=opts.get("placement_group"),
                bundle_index=opts.get("placement_group_bundle_index", -1),
                runtime_env=wire_env,
                strategy=opts.get("strategy"),
                drain_migration=bool(opts.get("drain_migration", True)),
                timeout=None,
            )
            return reply

        reply = self.run_coro(_create())
        self._actor_addr_cache[actor_id.hex()] = (reply["addr"], reply["incarnation"])
        return actor_id, reply["addr"]

    async def _actor_addr(self, actor_id_hex: str, refresh: bool = False) -> str:
        if not refresh:
            cached = self._actor_addr_cache.get(actor_id_hex)
            if cached is not None:
                return cached[0]
        deadline = time.monotonic() + 30.0
        while True:
            reply = await self.head.call("get_actor", actor_id=actor_id_hex)
            state = reply["state"]
            if state == "alive":
                self._actor_addr_cache[actor_id_hex] = (reply["addr"], reply["incarnation"])
                return reply["addr"]
            if state == "dead":
                raise ActorDiedError(reply.get("death_cause") or "actor is dead")
            if time.monotonic() > deadline:
                raise ActorDiedError(f"actor stuck in state {state}")
            await asyncio.sleep(0.1)

    def submit_actor_task(self, actor_id: ActorID, method: str, args, kwargs, opts) -> List[ObjectRef]:
        num_returns = opts.get("num_returns", 1)
        task_id = TaskID.for_actor_task(actor_id)
        if TRACE_HOOK is not None:
            _tr = TRACE_HOOK.begin_task_trace(
                task_id.hex(), method, "actor_task", self.client_id, self.node_id,
            )
            if _tr is not None:
                opts = dict(opts, _trace=_tr)
        oids = [ObjectID.for_return(task_id, i) for i in range(num_returns)]
        for oid in oids:
            self.memory_store.mark_pending(oid)
            self._add_owned(oid)
        refs = [ObjectRef(oid, owner=self.client_id, worker=self) for oid in oids]
        self._pump_submit(
            lambda: self._actor_call_entry(actor_id, method, args, kwargs, opts, task_id, oids)
        )
        return refs

    def _actor_call_entry(self, actor_id, method, args, kwargs, opts, task_id, oids):
        """IO-thread fast path for argless actor calls on a known-alive
        incarnation: pure callback RPC, no coroutine.  Falls back to the
        retrying slow path for args, unknown addresses, or failures."""
        if args or kwargs:
            return self._submit_actor_task(actor_id, method, args, kwargs, opts, task_id, oids)
        aid = actor_id.hex()
        cached = self._actor_addr_cache.get(aid)
        conn = self._conns.get(cached[0]) if cached is not None else None
        if conn is None or conn.closed:
            return self._submit_actor_task(actor_id, method, args, kwargs, opts, task_id, oids)
        addr = cached[0]
        self._inflight_tasks[task_id.binary()] = addr

        def on_reply(msg):
            self._inflight_tasks.pop(task_id.binary(), None)
            if msg is None:
                if task_id.binary() in self._cancelled_tasks:
                    # force-cancel killed the actor process mid-call: the
                    # cancelled call must NOT re-execute on a restart
                    self._store_error(oids, TaskCancelledError("task was cancelled"))
                    return
                # connection died mid-call: slow path refreshes the actor
                # address (restart transparency) and retries
                t = spawn_bg(
                    self._submit_actor_task(actor_id, method, args, kwargs, opts, task_id, oids)
                )
                t.add_done_callback(self._report_task_exc)
            elif not msg.get("ok", True):
                import pickle

                e = pickle.loads(msg["err"])
                self._store_error(oids, e)
            else:
                self._store_results(oids, msg["results"], addr)

        trace = opts.get("_trace")
        num_returns = opts.get("num_returns", 1)
        retriable = opts.get("max_task_retries", 0) > 0

        def spec_fields():
            # shared by the template constants and the traced full encode
            return {
                "m": "actor_call",
                "actor_id": aid,
                "method": method,
                "owner": self.client_id,
                "args": [],
                "kwargs": {},
                "num_returns": num_returns,
                "retriable": retriable,
            }

        try:
            if trace is None:
                tmpl = self._task_spec_template(
                    ("actor", aid, method, num_returns), spec_fields,
                    retriable=retriable,
                )
                conn.call_template("actor_call", tmpl, on_reply, task_id.binary())
            else:
                # traced call: full spec with the trace context (the template
                # cannot carry a per-call field)
                if TRACE_HOOK is not None:
                    TRACE_HOOK.record_task_event(
                        task_id.hex(), None, "actor_task", "SCHEDULED",
                        trace=trace, worker_id=self.client_id,
                        node_id=self.node_id, target=aid,
                    )
                fields = spec_fields()
                del fields["m"]  # call_cb supplies the method
                conn.call_cb(
                    "actor_call", on_reply,
                    task_id=task_id.binary(),
                    **fields,
                    **{TRACE_FIELD: trace},
                )
        except ConnectionError:
            return self._submit_actor_task(actor_id, method, args, kwargs, opts, task_id, oids)
        return None

    async def _submit_actor_task(self, actor_id, method, args, kwargs, opts, task_id, oids):
        aid = actor_id.hex()
        try:
            specs, kwspecs = await self._build_args(args, kwargs)
        except asyncio.CancelledError:
            self._store_error(oids, TaskCancelledError("submission cancelled"))
            raise
        except BaseException as e:
            self._store_error(oids, e)
            return
        attempts = 1 + max(0, opts.get("max_task_retries", 0))
        # the +1 grants one address-refresh resend after an ambiguous
        # ConnectionError (restart transparency for idempotent calls).
        # no_resend suppresses it: incarnation-bound calls — compiled-DAG
        # actor loops — must fail with ActorDiedError rather than silently
        # re-run on the restarted actor, where they would reopen their
        # channels at stale stream positions and wedge the whole DAG.
        resend = 0 if opts.get("no_resend") else 1
        last_err: Optional[BaseException] = None
        refresh = False
        trace = opts.get("_trace")
        for _ in range(attempts + resend):
            try:
                addr = await self._actor_addr(aid, refresh=refresh)
                conn = await self.conn_to(addr)
                if trace is not None and TRACE_HOOK is not None:
                    TRACE_HOOK.record_task_event(
                        task_id.hex(), None, "actor_task", "SCHEDULED",
                        trace=trace, worker_id=self.client_id,
                        node_id=self.node_id, target=aid,
                    )
                self._inflight_tasks[task_id.binary()] = self._normalize_peer_addr(addr)
                try:
                    reply = await conn.call(
                        "actor_call",
                        actor_id=aid,
                        method=method,
                        task_id=task_id.binary(),
                        owner=self.client_id,
                        args=specs,
                        kwargs=kwspecs,
                        num_returns=opts.get("num_returns", 1),
                        retriable=opts.get("max_task_retries", 0) > 0,
                        timeout=None,
                        **({TRACE_FIELD: trace} if trace is not None else {}),
                    )
                finally:
                    self._inflight_tasks.pop(task_id.binary(), None)
                self._store_results(oids, reply["results"], addr)
                return
            except (ConnectionError, asyncio.TimeoutError) as e:
                if task_id.binary() in self._cancelled_tasks:
                    self._store_error(oids, TaskCancelledError("task was cancelled"))
                    return
                last_err = ActorDiedError(
                    f"actor {aid} died during call to {method!r}: {e}"
                )
                refresh = True
                await asyncio.sleep(0.05)
            except ActorDiedError as e:
                last_err = e
                break
        if task_id.binary() in self._cancelled_tasks:
            last_err = TaskCancelledError("task was cancelled")
        self._store_error(oids, last_err or ActorDiedError("actor call failed"))

    def cancel(self, ref, force: bool = False, recursive: bool = False):
        """Cancel the task that produces `ref` (ray.cancel semantics,
        task_manager.h CancelTask role): a task still queued owner-side is
        dropped immediately; a running one gets TaskCancelledError raised in
        its executing thread (best-effort — lands at a bytecode boundary);
        force=True hard-kills the executing worker process instead (the only
        way out of C-level blocking calls).  Either way the ref's get()
        raises TaskCancelledError and the task is never retried.  A task
        that already finished is untouched (no-op).  `recursive` is accepted
        for API parity; child tasks cancel when their own refs are
        cancelled."""
        oid = ref.id
        task_id = oid.task_id().binary()

        def _do():
            # task-level liveness first: a STREAM item's value arriving does
            # not mean the generator finished, and an in-flight push may
            # have already satisfied this particular return
            active = task_id in self._inflight_tasks or task_id in self._streams
            if not active:
                e = self.memory_store.get_entry(oid)
                if e is not None and e.state != "pending":
                    return  # already finished: no-op
            self._cancelled_tasks.add(task_id)
            # queued in a backlog: drop it right now
            for pool in self._lease_pools.values():
                for item in list(pool.backlog):
                    if item[0].binary() == task_id:
                        pool.backlog.remove(item)
                        pool.inflight_total -= 1
                        self._store_error(
                            item[3], TaskCancelledError("task was cancelled")
                        )
                        return
            addr = self._inflight_tasks.get(task_id)
            if addr is not None:
                conn = self._conns.get(addr)
                if conn is not None and not conn.closed:
                    try:
                        conn.notify("cancel", task_id=task_id, force=force)
                    except ConnectionError:
                        pass  # worker already gone; death path settles the ref
            else:
                # not pushed yet (awaiting a lease / resolving args): settle
                # THIS ref immediately — a cancelled task must not stay
                # pending until cluster capacity frees — and leave the
                # cancelled mark so the submit path releases its lease and
                # settles any sibling return oids when it wakes
                self.memory_store.put_error(
                    oid, TaskCancelledError("task was cancelled")
                )

        self.loop.call_soon_threadsafe(_do)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.head_call("kill_actor", actor_id=actor_id.hex(), no_restart=no_restart)

    def get_actor_info(self, name: Optional[str] = None, actor_id: Optional[str] = None) -> dict:
        return self.head_call("get_actor", name=name, actor_id=actor_id)

    # ------------------------------------------------------------- cluster
    def head_call(self, method: str, **fields) -> dict:
        """Blocking control-plane RPC.  Rides through a head restart: while
        the housekeeping loop is redialing, retry instead of surfacing
        ConnectionError (gcs client reconnection semantics)."""
        deadline = time.monotonic() + 15.0
        while True:
            try:
                return self.run_coro(self.head.call(method, **fields))
            except FencedError:
                # the head refused our stamped incarnation: death verdict.
                # Never retry — completing this call would be the duplicate
                # side effect fencing exists to prevent.
                self._fence_now()
                raise
            except ConnectionError:
                if (
                    self._stopped
                    or self._head_fenced
                    or time.monotonic() > deadline
                ):
                    raise
                time.sleep(0.25)

    def shutdown(self, stop_cluster: bool = False):
        self._stopped = True
        try:
            self.reference_counter.flush()
        except Exception:
            pass
        if stop_cluster and self.head is not None and not self.head.closed:
            try:
                self.run_coro(self.head.call("job_stop", timeout=2.0), timeout=3.0)
            except Exception:
                pass

        async def _close_all():
            # force out any debounce-window refcount updates before the
            # connections close (the timer may not have fired yet)
            try:
                self._flush_ref_pending()
            except Exception:
                pass
            # last lifecycle events out before the head connection closes
            try:
                self._flush_task_events()
            except Exception:
                pass
            # cancel + await housekeeping first: a bare loop.stop() would
            # destroy it mid-await ("Task was destroyed but it is pending")
            task = getattr(self, "_housekeeping_task", None)
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                # awaiting a task WE just cancelled: its CancelledError is
                # the expected completion signal, not our own cancellation
                except (asyncio.CancelledError, Exception):  # ca-lint: ignore[async-swallowed-cancel]
                    pass
            if self.head is not None:
                await self.head.close()
            for c in self._conns.values():
                await c.close()
            if self._p2p_server is not None:
                await self._p2p_server.stop()
                for a in self._p2p_server.bound_addrs:
                    if a.startswith("unix:"):
                        try:
                            os.unlink(a[5:])
                        except OSError:
                            pass
                self._p2p_server = None

        try:
            self.run_coro(_close_all(), timeout=5)
        except Exception:
            pass
        if self._io_thread is not None:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._io_thread.join(timeout=2)
        set_global_worker(None)
