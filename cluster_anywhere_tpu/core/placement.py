"""Placement groups (analogue of python/ray/util/placement_group.py).

A placement group atomically reserves a list of resource bundles; tasks and
actors scheduled into a bundle consume from that reservation.  Strategies
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) control node placement; on the
current single-node milestone they are recorded and validated but equivalent.

A PG whose demand exceeds the cluster's *total* capacity errors immediately
(truly infeasible); one that merely exceeds currently-free resources is
PENDING and created FIFO as resources release — ready()/wait() block on that
(mirrors GcsPlacementGroupManager's PENDING->CREATED lifecycle).
"""

from __future__ import annotations

import asyncio

from typing import Dict, List, Optional

from .errors import PlacementGroupError
from .ids import PlacementGroupID
from .worker import global_worker

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def ready(self):
        """Returns an ObjectRef that resolves (to True) once the head has
        reserved all bundles — immediately for a created PG, later for a
        pending one; errors if the PG is removed while pending."""
        w = global_worker()
        ref = w.new_owned_ref()
        oid = ref.id
        pg_hex = self.id.hex()

        async def _wait():
            try:
                await w.head.call("pg_wait", pg_id=pg_hex)
                w.memory_store.put_value(oid, True)
            except asyncio.CancelledError:
                # loop shutdown: unblock ref waiters, then stay cancelled
                w.memory_store.put_error(oid, ConnectionError("pg_wait cancelled"))
                raise
            except BaseException as e:  # noqa: BLE001 - propagate via the ref
                w.memory_store.put_error(oid, e)

        asyncio.run_coroutine_threadsafe(_wait(), w.loop)
        return ref

    def wait(self, timeout_seconds: float = 30) -> bool:
        try:
            r = global_worker().head_call(
                "pg_wait", pg_id=self.id.hex(), wait_timeout=timeout_seconds
            )
        except PlacementGroupError:
            return False
        return bool(r.get("ready"))

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    bundle_label_selectors: Optional[List[Optional[dict]]] = None,
) -> PlacementGroup:
    """`bundle_label_selectors` optionally gives one label selector per bundle
    (dict of label key -> In/NotIn/Exists/DoesNotExist or bare string); that
    bundle is then only placed on nodes matching it — e.g. pin a bundle per
    TPU slice via {"ca.io/tpu-slice-name": "pod-a"}."""
    if strategy not in STRATEGIES:
        raise ValueError(f"strategy must be one of {STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be non-negative")
    wire_labels = None
    if bundle_label_selectors is not None:
        from .scheduling_strategies import selector_wire

        if len(bundle_label_selectors) != len(bundles):
            raise ValueError("bundle_label_selectors must match bundles 1:1")
        wire_labels = [selector_wire(s) for s in bundle_label_selectors]
    pg_id = PlacementGroupID.from_random()
    w = global_worker()
    w.head_call(
        "create_pg",
        pg_id=pg_id.hex(),
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy,
        bundle_labels=wire_labels,
    )
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    global_worker().head_call("remove_pg", pg_id=pg.id.hex())


def placement_group_table() -> List[dict]:
    return global_worker().head_call("list_pgs")["pgs"]
