"""Placement groups (analogue of python/ray/util/placement_group.py).

A placement group atomically reserves a list of resource bundles; tasks and
actors scheduled into a bundle consume from that reservation.  Strategies
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) control node placement; on the
current single-node milestone they are recorded and validated but equivalent.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .errors import PlacementGroupError
from .ids import PlacementGroupID
from .worker import global_worker

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def ready(self):
        """Returns an ObjectRef resolving when the PG is created (already
        created synchronously on this milestone)."""
        return global_worker().put(True)

    def wait(self, timeout_seconds: float = 30) -> bool:
        return True

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in STRATEGIES:
        raise ValueError(f"strategy must be one of {STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be non-negative")
    pg_id = PlacementGroupID.from_random()
    w = global_worker()
    w.head_call(
        "create_pg",
        pg_id=pg_id.hex(),
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy,
    )
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    global_worker().head_call("remove_pg", pg_id=pg.id.hex())


def placement_group_table() -> List[dict]:
    return global_worker().head_call("list_pgs")["pgs"]
