"""Top-level API: init/shutdown/remote/get/put/wait and cluster introspection
(analogue of python/ray/_private/worker.py's public functions).
"""

from __future__ import annotations

import inspect
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from .actor import ActorClass
from .config import CAConfig, get_config, set_config
from .object_ref import ObjectRef
from .remote_function import RemoteFunction
from .worker import Worker, global_worker, set_global_worker, try_global_worker

_head_proc: Optional[subprocess.Popen] = None
_session_dir: Optional[str] = None


def is_initialized() -> bool:
    return try_global_worker() is not None


def _sweep_stale_sessions(root: str):
    """GC session dirs (and their /dev/shm segments) whose head process is
    gone — hard-killed clusters can't clean up after themselves."""
    import shutil

    for name in os.listdir(root):
        path = os.path.join(root, name)
        if name.startswith("client_"):
            # client-mode scratch (pull caches): live clients refresh their
            # dir mtime every 30s (worker housekeeping), so a >1h-stale
            # mtime means abandoned — no pid probe (the embedded pid may
            # have been recycled by an unrelated process, which would make
            # the dir unreclaimable forever)
            try:
                if time.time() - os.path.getmtime(path) > 3600:
                    shutil.rmtree(path, ignore_errors=True)
                    shutil.rmtree(os.path.join("/dev/shm", name), ignore_errors=True)
            except OSError:
                pass
            continue
        if not name.startswith("session_"):
            continue
        ready = os.path.join(path, "head.ready")
        pid = None
        try:
            pid = int(open(ready).read().strip())
        except (OSError, ValueError):
            # head.ready not written yet: a concurrent init may own this dir —
            # only sweep if it has been around a while
            try:
                if time.time() - os.path.getmtime(path) < 120:
                    continue
            except OSError:
                continue
        alive = False
        if pid is not None:
            try:
                os.kill(pid, 0)
                alive = True
            except (ProcessLookupError, PermissionError):
                pass
        if not alive:
            # dead head, but a recently-touched dir may be a cluster mid
            # head-restart (head FT): leave young sessions alone — a later
            # init will sweep them once they are genuinely abandoned
            try:
                if time.time() - os.path.getmtime(path) < 120:
                    continue
            except OSError:
                continue
            shutil.rmtree(path, ignore_errors=True)
            shutil.rmtree(os.path.join("/dev/shm", name), ignore_errors=True)


def _find_session(address: str, root: str) -> str:
    """Resolve `address` to a running session dir ("auto" = newest)."""
    def _alive(path: str) -> bool:
        try:
            pid = int(open(os.path.join(path, "head.ready")).read().strip())
        except (OSError, ValueError):
            return False
        try:
            os.kill(pid, 0)
            return True
        except PermissionError:
            return True  # EPERM: process exists, owned by another user
        except ProcessLookupError:
            return False

    if address != "auto":
        if _alive(address):
            return address
        raise ConnectionError(f"no running cluster at {address!r}")
    if os.path.isdir(root):
        for name in sorted(os.listdir(root), reverse=True):
            path = os.path.join(root, name)
            if _alive(path):
                return path
    raise ConnectionError(f"no running cluster found under {root}")


def init(
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    config: Optional[CAConfig] = None,
    session_dir: Optional[str] = None,
    address: Optional[str] = None,
    **config_overrides,
) -> Dict[str, Any]:
    """Start a local cluster (head + worker pool) and connect this process as
    the driver — or, with `address=` ("auto" or a session dir), connect to an
    already-running cluster as an additional driver.
    Mirrors ray.init (python/ray/_private/worker.py:1275).

    A tcp `address=` may be a comma-separated list naming the active head
    plus warm standbys ("tcp:h1:6379,tcp:h2:6379"): the driver dials the
    first reachable entry and fails over along the list (plus any standbys
    learned at register time) when the active head dies mid-session.

    Config overrides pass as keywords, e.g. `init(log_to_driver=False)` to
    opt this driver out of the cluster log stream (worker prints echoed with
    task/worker/node attribution — see util/logplane.py)."""
    global _head_proc, _session_dir
    if is_initialized():
        raise RuntimeError("already initialized; call shutdown() first")
    cfg = config or CAConfig()
    for k, v in config_overrides.items():
        if not hasattr(cfg, k):
            raise ValueError(f"unknown config key {k!r}")
        setattr(cfg, k, v)
    if address is not None:
        if any(
            x is not None
            for x in (num_cpus, num_tpus, resources, object_store_memory, session_dir)
        ):
            raise ValueError(
                "resource/session arguments have no effect when joining an "
                "existing cluster via address=; the head's values apply"
            )
        set_config(cfg)
        if address.startswith("tcp:"):
            # remote driver (Ray-Client analogue, ray:// role): connect to
            # the head's TCP endpoint from a host with no session dir.  Puts
            # upload to the head's store; worker/actor addresses arrive as
            # TCP duals; pulled objects cache in a client-private namespace.
            root = cfg.session_dir_root
            os.makedirs(root, exist_ok=True)
            sdir = os.path.join(root, f"client_{int(time.time()*1000)}_{os.getpid()}")
            os.makedirs(sdir, exist_ok=True)
            _session_dir = sdir
            w = Worker(
                mode="driver",
                session_dir=sdir,
                head_sock=address,
                config=cfg,
                client_mode=True,
            )
            set_global_worker(w)
            w.connect()
            return {
                "session_dir": sdir,
                "node_id": w.node_id,
                "resources": w.total_resources,
            }
        sdir = _find_session(address, cfg.session_dir_root)
        _session_dir = sdir
        w = Worker(
            mode="driver",
            session_dir=sdir,
            head_sock=os.path.join(sdir, "head.sock"),
            config=cfg,
        )
        set_global_worker(w)
        w.connect()
        return {
            "session_dir": sdir,
            "node_id": w.node_id,
            "resources": w.total_resources,
        }
    if object_store_memory is not None:
        cfg.object_store_memory = object_store_memory
    set_config(cfg)

    if num_cpus is None:
        num_cpus = min(os.cpu_count() or 4, 16)
    total: Dict[str, float] = {"CPU": float(num_cpus)}
    from . import accelerators

    if num_tpus is None:
        # detect TPU chips without importing jax (env markers or /dev/accel*;
        # accelerators.py = tpu.py TPUAcceleratorManager analogue)
        num_tpus = int(
            os.environ.get("CA_NUM_TPUS") or accelerators.num_tpu_chips()
        )
    if num_tpus:
        total["TPU"] = float(num_tpus)
        # topology-derived markers: accelerator type (TPU-V5E) and, on pod
        # worker 0, the pod-head resource (TPU-v5e-16-head) for SPMD pinning
        for k, v in accelerators.additional_resources().items():
            total.setdefault(k, v)
    total["memory"] = float(cfg.object_store_memory)
    if resources:
        total.update({k: float(v) for k, v in resources.items()})

    if session_dir is None:
        root = cfg.session_dir_root
        os.makedirs(root, exist_ok=True)
        _sweep_stale_sessions(root)
        session_dir = os.path.join(root, f"session_{int(time.time()*1000)}_{os.getpid()}")
    os.makedirs(session_dir, exist_ok=True)
    _session_dir = session_dir

    env = dict(os.environ)
    env["CA_SESSION_DIR"] = session_dir
    env["CA_CONFIG_JSON"] = cfg.to_json()
    env["CA_RESOURCES"] = json.dumps(total)
    # child processes must find this package regardless of the driver's cwd
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    head_log = open(os.path.join(session_dir, "head.log"), "ab")
    _head_proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_anywhere_tpu.core.head"],
        env=env,
        stdout=head_log,
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    head_log.close()
    ready = os.path.join(session_dir, "head.ready")
    deadline = time.monotonic() + 30
    while not os.path.exists(ready):
        if _head_proc.poll() is not None:
            raise RuntimeError(
                f"head process exited with {_head_proc.returncode}; "
                f"see {session_dir}/head.log"
            )
        if time.monotonic() > deadline:
            raise RuntimeError("timed out waiting for head to start")
        time.sleep(0.01)

    w = Worker(
        mode="driver",
        session_dir=session_dir,
        head_sock=os.path.join(session_dir, "head.sock"),
        config=cfg,
    )
    set_global_worker(w)
    w.connect()
    return {"session_dir": session_dir, "node_id": w.node_id, "resources": total}


def shutdown():
    global _head_proc, _session_dir
    w = try_global_worker()
    client_cleanup = None
    if w is not None:
        if w.client_mode:
            # client-private scratch: this host's pull-cache namespace and
            # session dir are invisible to the cluster — remove them here
            client_cleanup = (w.session_name, w.session_dir)
        # only a driver that spawned the head tears the cluster down; a
        # driver that joined via address= just disconnects
        w.shutdown(stop_cluster=_head_proc is not None)
    if client_cleanup is not None:
        import shutil

        shutil.rmtree(os.path.join("/dev/shm", client_cleanup[0]), ignore_errors=True)
        shutil.rmtree(client_cleanup[1], ignore_errors=True)
    if _head_proc is not None:
        try:
            _head_proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            _head_proc.kill()
            _head_proc.wait(timeout=5)
        _head_proc = None
    _session_dir = None


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    return global_worker().get(refs, timeout=timeout)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = False) -> None:
    """Cancel the task producing `ref` (ray.cancel analogue).  Queued tasks
    drop immediately; running ones get TaskCancelledError raised at their
    next bytecode boundary; force=True kills the executing worker process
    (for C-level blocking calls).  get(ref) then raises TaskCancelledError;
    cancelled tasks are never retried.  No-op on finished tasks."""
    global_worker().cancel(ref, force=force, recursive=recursive)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
):
    return global_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def remote(*args, **kwargs):
    """@remote decorator for functions and classes, with or without options:
    @remote / @remote(num_cpus=2, num_returns=2)."""

    def make(obj, opts):
        if inspect.isclass(obj):
            return ActorClass(obj, opts)
        if callable(obj):
            return RemoteFunction(obj, opts)
        raise TypeError("@remote must decorate a function or class")

    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        return make(args[0], {})
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return lambda obj: make(obj, kwargs)


def nodes() -> List[dict]:
    return global_worker().head_call("nodes")["nodes"]


def cluster_resources() -> Dict[str, float]:
    return global_worker().head_call("cluster_resources")["total"]


def available_resources() -> Dict[str, float]:
    return global_worker().head_call("cluster_resources")["available"]


def cluster_stats() -> Dict[str, Any]:
    return global_worker().head_call("stats")["stats"]


def drain_node(
    node_id: str, *, reason: str = "manual", deadline_s: Optional[float] = None
) -> Dict[str, Any]:
    """Gracefully drain a node (DrainNode protocol analogue): stop new
    placement on it, recall its delegated lease blocks, migrate its actors
    and sole-copy objects to survivors, and give running tasks until the
    deadline before the kill — whose retries do NOT consume the tasks'
    max_retries budget.  `reason` is one of "manual" | "idle" | "preemption";
    `deadline_s` defaults to the cluster's drain_deadline_s.  Returns the
    head's reply ({"state": "draining", "deadline_s": ...}, or the current
    state when the node is already draining/drained/dead)."""
    fields: Dict[str, Any] = {"node_id": node_id, "reason": reason}
    if deadline_s is not None:
        fields["deadline_s"] = float(deadline_s)
    return global_worker().head_call("drain_node", **fields)


def timeline(filename: Optional[str] = None, *, limit: int = 100_000) -> List[dict]:
    """Chrome-trace/Perfetto events of task lifecycles, with flow arrows
    between submit and execute spans when tracing is enabled (see
    util.state.timeline)."""
    from ..util.state import timeline as _timeline

    return _timeline(filename, limit=limit)
