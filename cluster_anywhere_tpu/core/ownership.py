"""The ownership plane: owner-resident object lifetime.

The process that creates an object (its *owner* — `ReferenceCounter._owned`
already marks this) is the authority for its cluster-wide refcount and its
spill decision, the NSDI'21 ownership protocol of the reference
(src/ray/core_worker/reference_count.h AddBorrowedObject /
WaitForRefRemoved): when a ref crosses a process boundary, the borrower
registers with the owner over a direct worker<->worker connection
(`owner_refs`), NOT with the head.  The head is demoted to registry-of-owners
(obj_created / obj_release keep its location snapshot current) and failover
arbiter: each owner ships a versioned digest of its ledger with its
heartbeats (`owner_sync`), and when an owner dies the head adopts the
orphaned objects from the last digest so borrowers drain through the central
path without leaking shm segments or spill files.

This module is the bookkeeping half; the wiring lives in worker.py (routing,
RPC serving, GC actions) and head.py (relay, adoption, registry settlement).

`OwnerLedger` deliberately mirrors the head's holder semantics so the two
authorities stay interchangeable per object:
- holder ids are client ids, "<cid>#v" value pins, and "t:<cid>:<n>" transit
  tokens;
- a dec from the owner itself marks `released` (head: owner_released);
- transit acks that race ahead of their pin leave a spent-token tombstone;
- holder adds for unknown oids wait in a bounded, grace-windowed pending map
  (head: `_early_refs`) instead of relying on arrival order.

`DeltaReporter` is the ray_syncer-style versioned delta channel used by the
node agent's heartbeat loop: components (load, lease occupancy, pressure) are
re-sent only when their payload changes; an unchanged tick degenerates to a
~20-byte keepalive, and a reconnect triggers a full resync.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

# Ownership-plane counters (same plain-int discipline as protocol.WIRE_STATS
# / worker.LEASE_STATS: owned-thread increments, flusher-only reads).
# Shipped as ca_owner_* counters by util/metrics and summed into bench.py's
# BENCH-json `ownerplane` block.
OWNER_STATS: Dict[str, int] = {
    "refs_settled_local": 0,   # inc/dec applied to this process's own ledger
    "refs_sent_owner": 0,      # inc/dec sent to another process's ledger
    "refs_recv": 0,            # owner_refs updates served by this ledger
    "refs_head_fallback": 0,   # inc/dec that fell back to the head path
    "owner_gc": 0,             # objects whose lifetime this ledger settled
    "owner_gc_head_down": 0,   # of those, settled (and freed) with no head
    "pins_served": 0,          # owner_pin requests answered authoritatively
    "pending_expired": 0,      # grace-expired pending borrower adds (sweep)
    "spills_decided": 0,       # spill free/defer decisions made owner-side
    "syncs_sent": 0,           # owner_sync digests shipped to the head
    "syncs_full": 0,           # of those, full resyncs (reconnect)
}


def owner_stats() -> Dict[str, int]:
    """Snapshot of this process's ownership-plane counters."""
    return dict(OWNER_STATS)


# ---------------------------------------------------------------- log helper
_warn_lock = threading.Lock()
_warn_last: Dict[str, float] = {}
_warn_suppressed: Dict[str, int] = {}


def warn_ratelimited(key: str, msg: str, period_s: float = 10.0) -> None:
    """Print a warning at most once per `period_s` per key (with a
    suppressed-repeat count), through the log plane's capture when installed.
    Used where callbacks used to swallow exceptions with a bare `pass` —
    a GC bug must be visible without turning a hot loop into a log flood."""
    now = time.monotonic()
    with _warn_lock:
        last = _warn_last.get(key, 0.0)
        if now - last < period_s:
            _warn_suppressed[key] = _warn_suppressed.get(key, 0) + 1
            return
        _warn_last[key] = now
        n = _warn_suppressed.pop(key, 0)
    suffix = f" [{n} similar suppressed]" if n else ""
    # plain print: the log plane's StreamCapture (util/logplane) stamps and
    # ships stdout, so this reaches `ca logs` / the driver with attribution
    print(f"[ca][warn] {msg}{suffix}", flush=True)


class _Ent:
    """One owned object's cluster-wide lifetime state."""

    __slots__ = (
        "holders", "released", "registered", "shm_name", "size",
        "spill_path", "pending_free", "contains",
    )

    def __init__(self):
        self.holders: Set[str] = set()
        self.released = False      # the owner dropped its last local handle
        self.registered = False    # obj_created reached (or targets) the head
        self.shm_name: Optional[str] = None  # primary copy (owner's node)
        self.size = 0
        self.spill_path: Optional[str] = None
        # old shm slice of a spilled-while-pinned object: reclaimed by the
        # owner when the last "#v" value pin drops (head: rec.pending_free)
        self.pending_free: Optional[str] = None
        # nested ObjectRefs serialized inside this object's payload, as
        # (oid, owner_cid) pairs: each inner object carries a
        # "cnt:<container-hex>" holder at ITS owner's ledger for as long as
        # this entry lives (borrowing containment edges, owner-resident form).
        # The owner cid travels with the oid because the container's owner
        # may never deserialize the payload — it must still be able to route
        # the release to the right ledger.
        self.contains: List[Tuple[bytes, Optional[str]]] = []


class OwnerLedger:
    """Borrower ledger for the objects THIS process owns.

    Thread-safe (user threads release handles; the IO loop serves borrower
    RPCs and flushes).  Mutations bump `version` and mark the entry dirty so
    `digest_delta()` can ship owner_sync deltas; `on_clear` fires (outside
    the lock) when an entry's lifetime fully settles — owner released and no
    borrowers — handing GC to the worker; `on_pin_zero` fires when the last
    "#v" value pin drops, releasing a spill's pending old slice.
    """

    def __init__(
        self,
        owner_id: str,
        on_clear: Optional[Callable[[List[Tuple[bytes, dict]]], None]] = None,
        on_pin_zero: Optional[Callable[[bytes], None]] = None,
        pending_grace_s: float = 600.0,
    ):
        self.owner_id = owner_id
        self.on_clear = on_clear
        self.on_pin_zero = on_pin_zero
        self._lock = threading.Lock()
        self._ents: Dict[bytes, _Ent] = {}
        # holder adds that raced ahead of register() (mirrors the head's
        # _early_refs, bounded by the same explicit grace window)
        self._pending: Dict[bytes, Tuple[float, Set[str]]] = {}
        self._pending_grace_s = pending_grace_s
        # transit acks that arrived before their pin (different sockets)
        self._spent_transit: Dict[str, float] = {}
        # ttl-opted transit pins (owner_locate serving): reclaimed when the
        # ack was lost in flight
        self._ttl_pins: Dict[str, Tuple[float, List[bytes]]] = {}
        # delta-sync state for owner_sync digests
        self.version = 0
        self._dirty: Set[bytes] = set()
        self._removed: Set[bytes] = set()

    # ------------------------------------------------------------- lifecycle
    def register(self, oid: bytes) -> None:
        """The owner minted this object (add_owned time).  Must precede any
        borrower's knowledge of the ref — the ref cannot leave the process
        before it exists — so pending adds are adopted here."""
        with self._lock:
            if oid in self._ents:
                return
            ent = self._ents[oid] = _Ent()
            pend = self._pending.pop(oid, None)
            if pend is not None:
                ent.holders |= pend[1]
            self._mark_dirty_locked(oid)

    def set_location(
        self, oid: bytes, shm_name: Optional[str], size: int,
        registered: bool = True,
    ) -> None:
        """Record the primary copy's location (obj_created time) so the owner
        can serve owner_pin/owner_locate even after its local read-cache
        entry is evicted at local-zero.  Update-only: an entry whose lifetime
        already settled (every handle died before the data arrived) must not
        be resurrected — the head's registry entry is the orphan's record,
        reaped with the owner's other state at disconnect, as before."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return
            ent.shm_name = shm_name
            ent.size = size
            ent.spill_path = None
            if registered:
                ent.registered = True

    def set_contains(
        self, oid: bytes, refs: List[Tuple[bytes, Optional[str]]]
    ) -> Optional[List[Tuple[bytes, Optional[str]]]]:
        """Record the containment edges of an owned container; returns the
        PREVIOUS edge list (re-registration, e.g. reconstruction re-ran the
        creating task) so the caller can release the stale edges — or None
        when the container is no longer tracked (its lifetime settled before
        the edges arrived): the caller must release the NEW edges instead."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return None
            old, ent.contains = ent.contains, list(refs)
            return old

    def spill_transition(self, oid: bytes, path: str) -> Optional[bool]:
        """Owner-side spill decision, atomic with the relocation: returns
        whether zero-copy value pins hold the old slice (True = defer its
        reclaim to the last pin drop — the old slice is remembered as
        pending_free and handed back via pop_pending_free on the pin-zero
        callback; False = the spiller frees it now), or None when the object
        is no longer tracked (GC won the race — the spiller drops the file
        and frees the slice)."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return None
            pinned = any(h.endswith("#v") for h in ent.holders)
            if pinned:
                ent.pending_free = ent.shm_name
            ent.spill_path = path
            ent.shm_name = None
            self._mark_dirty_locked(oid)
            OWNER_STATS["spills_decided"] += 1
            return pinned

    def pop_pending_free(self, oid: bytes) -> Optional[str]:
        """Take the spilled-while-pinned old slice awaiting reclaim (fired
        from the on_pin_zero callback, or by GC settling the entry)."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return None
            name, ent.pending_free = ent.pending_free, None
            return name

    def tracks(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._ents

    def entry_info(self, oid: bytes) -> Optional[dict]:
        """Location snapshot for owner_locate/owner_pin serving (no pin)."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return None
            return {
                "shm_name": ent.shm_name, "size": ent.size,
                "spill_path": ent.spill_path, "registered": ent.registered,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ents)

    # --------------------------------------------------------------- holders
    def _mark_dirty_locked(self, oid: bytes) -> None:
        self.version += 1
        self._dirty.add(oid)

    def apply(
        self,
        inc: List[bytes],
        dec: List[bytes],
        as_id: str,
        ttl: bool = False,
    ) -> None:
        """Apply one obj_refs-shaped update — the exact semantics of the
        head's `_h_obj_refs`, owner-resident."""
        cleared: List[Tuple[bytes, dict]] = []
        pin_zero: List[bytes] = []
        with self._lock:
            if as_id in self._spent_transit:
                # the receiver already acked this transit: the pin is moot
                del self._spent_transit[as_id]
            else:
                if inc and ttl and as_id.startswith("t:"):
                    self._ttl_pins[as_id] = (time.monotonic(), list(inc))
                for oid in inc:
                    ent = self._ents.get(oid)
                    if ent is not None:
                        ent.holders.add(as_id)
                        self._mark_dirty_locked(oid)
                    else:
                        # borrower registration racing object re-creation
                        # (reconstruction) — park it under the grace window
                        pend = self._pending.get(oid)
                        if pend is None:
                            pend = self._pending[oid] = (time.monotonic(), set())
                        pend[1].add(as_id)
            for oid in dec:
                ent = self._ents.get(oid)
                if ent is None:
                    pend = self._pending.get(oid)
                    if pend is not None:
                        pend[1].discard(as_id)
                        if not pend[1]:
                            del self._pending[oid]
                    continue
                ent.holders.discard(as_id)
                if as_id == self.owner_id:
                    ent.released = True
                self._mark_dirty_locked(oid)
                if (
                    as_id.endswith("#v")
                    and not any(h.endswith("#v") for h in ent.holders)
                ):
                    pin_zero.append(oid)
                if ent.released and not ent.holders:
                    cleared.append((oid, self._drop_locked(oid)))
        self._fire(cleared, pin_zero)

    def _drop_locked(self, oid: bytes) -> dict:
        ent = self._ents.pop(oid, None)
        self.version += 1
        self._dirty.discard(oid)
        self._removed.add(oid)
        if ent is None:
            return {}
        return {
            "registered": ent.registered, "shm_name": ent.shm_name,
            "size": ent.size, "spill_path": ent.spill_path,
            "pending_free": ent.pending_free, "contains": ent.contains,
        }

    def _fire(self, cleared: List[Tuple[bytes, dict]], pin_zero: List[bytes]) -> None:
        """Run callbacks outside the lock; failures are logged (rate-limited)
        rather than swallowed — a silent GC bug is invisible otherwise."""
        if pin_zero and self.on_pin_zero is not None:
            for oid in pin_zero:
                try:
                    self.on_pin_zero(oid)
                except Exception as e:
                    warn_ratelimited(
                        "ledger-pin-zero",
                        f"ownership ledger pin-release callback failed: {e!r}",
                    )
        if cleared and self.on_clear is not None:
            try:
                self.on_clear(cleared)
            except Exception as e:
                warn_ratelimited(
                    "ledger-clear",
                    f"ownership ledger GC callback failed: {e!r}",
                )

    def pin(self, oid: bytes, as_id: str) -> Optional[dict]:
        """Atomic pin + locate (the owner-side `obj_pin`): registering the
        pin and reading the current location under one lock means a reader
        can never map a slice this owner's spiller is about to recycle."""
        with self._lock:
            ent = self._ents.get(oid)
            if ent is None:
                return None
            if ent.shm_name is None and ent.spill_path is None:
                return None  # inline/pending/re-homed: head or value path
            ent.holders.add(as_id)
            self._mark_dirty_locked(oid)
            OWNER_STATS["pins_served"] += 1
            return {
                "shm_name": ent.shm_name, "size": ent.size,
                "spill_path": ent.spill_path,
            }

    def transit_done(
        self, token: str, oids: List[bytes], cid: str, register: bool = True
    ) -> None:
        """Receiver ack of in-transit borrowed refs (head `_h_transit_done`
        semantics): register the receiver, release the token pin, tombstone
        tokens whose pin hasn't landed yet."""
        cleared: List[Tuple[bytes, dict]] = []
        with self._lock:
            self._ttl_pins.pop(token, None)
            seen = False
            for oid in oids:
                ent = self._ents.get(oid)
                if ent is not None:
                    if register:
                        ent.holders.add(cid)
                    if token in ent.holders:
                        seen = True
                        ent.holders.discard(token)
                    self._mark_dirty_locked(oid)
                    if ent.released and not ent.holders:
                        cleared.append((oid, self._drop_locked(oid)))
                else:
                    pend = self._pending.get(oid)
                    if pend is None and register:
                        pend = self._pending[oid] = (time.monotonic(), set())
                    if pend is not None:
                        if register:
                            pend[1].add(cid)
                        if token in pend[1]:
                            seen = True
                            pend[1].discard(token)
            if not seen:
                self._spent_transit[token] = time.monotonic()
        self._fire(cleared, [])

    def purge_holder(self, cid: str) -> None:
        """A borrower process died (head `client_gone` broadcast): its
        holder id, value pin, transit tokens, and containment edges (the
        "cnt:<cid>:<container>" holders its containers' settlement would
        have dec'd) can never dec."""
        pin_id = f"{cid}#v"
        transit_prefix = f"t:{cid}:"
        edge_prefix = f"cnt:{cid}:"
        cleared: List[Tuple[bytes, dict]] = []
        pin_zero: List[bytes] = []
        with self._lock:
            for oid, ent in list(self._ents.items()):
                stale = [
                    h for h in ent.holders
                    if h == cid or h == pin_id
                    or h.startswith(transit_prefix)
                    or h.startswith(edge_prefix)
                ]
                if not stale:
                    continue
                had_pin = any(h.endswith("#v") for h in ent.holders)
                ent.holders.difference_update(stale)
                self._mark_dirty_locked(oid)
                if had_pin and not any(h.endswith("#v") for h in ent.holders):
                    pin_zero.append(oid)
                if ent.released and not ent.holders:
                    cleared.append((oid, self._drop_locked(oid)))
            for tok in [
                t for t in self._ttl_pins if t.startswith(transit_prefix)
            ]:
                del self._ttl_pins[tok]
        self._fire(cleared, pin_zero)

    # ----------------------------------------------------------------- sweep
    def sweep(self, now: Optional[float] = None) -> int:
        """Periodic reclamation (worker housekeeping): expire pending holder
        adds past the grace window and ttl transit pins whose ack was lost.
        Returns the number of expired pending entries (observability)."""
        if now is None:
            now = time.monotonic()
        expired = 0
        cleared: List[Tuple[bytes, dict]] = []
        with self._lock:
            cutoff = now - self._pending_grace_s
            for oid in [
                o for o, (ts, _) in self._pending.items() if ts < cutoff
            ]:
                del self._pending[oid]
                expired += 1
            tok_cutoff = now - 600.0
            for tok in [
                t for t, (ts, _) in self._ttl_pins.items() if ts < tok_cutoff
            ]:
                _, oids = self._ttl_pins.pop(tok)
                for oid in oids:
                    ent = self._ents.get(oid)
                    if ent is not None and tok in ent.holders:
                        ent.holders.discard(tok)
                        self._mark_dirty_locked(oid)
                        if ent.released and not ent.holders:
                            cleared.append((oid, self._drop_locked(oid)))
            spent_cutoff = now - 60.0
            for tok in [
                t for t, ts in self._spent_transit.items() if ts < spent_cutoff
            ]:
                del self._spent_transit[tok]
        self._fire(cleared, [])
        return expired

    # ----------------------------------------------------------- digest sync
    def digest_delta(self, full: bool = False) -> Optional[dict]:
        """The owner_sync payload: changed entries' borrower sets (the
        owner's own holds are excluded — they die with the owner) plus
        removed oids, or the full table on reconnect.  None = nothing to
        send (clean)."""
        with self._lock:
            if full:
                oids = list(self._ents)
                removed: List[bytes] = []
            else:
                if not self._dirty and not self._removed:
                    return None
                oids = [o for o in self._dirty if o in self._ents]
                removed = list(self._removed)
            self._dirty.clear()
            self._removed.clear()
            own_pin = f"{self.owner_id}#v"
            own_transit = f"t:{self.owner_id}:"
            entries = {}
            for oid in oids:
                ent = self._ents[oid]
                entries[oid] = {
                    "b": sorted(
                        h for h in ent.holders
                        if h != self.owner_id and h != own_pin
                        and not h.startswith(own_transit)
                    ),
                    "r": ent.released,
                    "g": ent.registered,
                }
            return {
                "v": self.version,
                "full": full,
                "e": entries,
                "rm": removed,
            }

    def holders_of(self, oid: bytes) -> Optional[Set[str]]:
        """Current holder set of one owned object (diagnostics/tests), or
        None when the ledger no longer tracks it."""
        with self._lock:
            ent = self._ents.get(oid)
            return set(ent.holders) if ent is not None else None


class DeltaReporter:
    """Versioned component-wise delta sync for the agent's node state (the
    ray_syncer.h role, head-ward form): `delta(components)` returns only the
    components whose payload changed since the last send — None when nothing
    did (the caller sends a bare keepalive) — and `reset()` forces the next
    delta to be a full resync (new head connection)."""

    def __init__(self):
        self._last: Dict[str, Any] = {}
        self.version = 0
        self._full_pending = True

    def reset(self) -> None:
        self._last = {}
        self._full_pending = True

    def delta(self, components: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        full = self._full_pending
        if full:
            changed = dict(components)
        else:
            changed = {
                k: v for k, v in components.items() if self._last.get(k) != v
            }
            if not changed:
                return None
        # deep-copy guard: store a stable snapshot for the next comparison
        import copy

        for k, v in changed.items():
            self._last[k] = copy.deepcopy(v)
        self.version += 1
        changed["v"] = self.version
        if full:
            changed["full"] = True
            self._full_pending = False
        return changed


def quantize_load(load: Dict[str, float]) -> Dict[str, float]:
    """Round load telemetry so jitter doesn't defeat delta sync: raw
    loadavg/mem fractions change every sample, which would re-send the
    component each tick and make the delta channel a full heartbeat with
    extra steps."""
    out = {}
    for k, v in load.items():
        out[k] = round(float(v), 1 if k == "load_1m" else 2)
    return out
