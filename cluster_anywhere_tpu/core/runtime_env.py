"""Per-task/actor runtime environments (analogue of the reference's
python/ray/_private/runtime_env/ — env_vars, working_dir, py_modules plugins
with content-addressed packaging through the head KV, reference
_private/runtime_env/packaging.py).

Driver side: `prepare()` packages local dirs into zips stored in the head KV
under their content digest (uploaded once, cached by digest). Worker side:
`RuntimeEnvContext.apply()` materializes the env — extracts packages into a
per-session cache, sets env vars / sys.path / cwd — and restores afterwards
(pool workers are reused; actors apply permanently in their dedicated
process).
"""

from __future__ import annotations

import hashlib
import io
import os
import shutil
import sys
import uuid
import zipfile
from typing import Any, Dict, List, Optional

_PKG_NS = "__pkgs__"
_MAX_PKG_BYTES = 100 * 1024 * 1024

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def validate(runtime_env: Dict[str, Any]):
    allowed = {"env_vars", "working_dir", "py_modules", "pip", "config"}
    unknown = set(runtime_env) - allowed
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)}")
    ev = runtime_env.get("env_vars")
    if ev is not None and not all(
        isinstance(k, str) and isinstance(v, str) for k, v in ev.items()
    ):
        raise ValueError("env_vars must be Dict[str, str]")
    if "pip" in runtime_env:
        normalize_pip_spec(runtime_env["pip"])  # raises on malformed specs


def normalize_pip_spec(spec: Any) -> Dict[str, Any]:
    """Accept the reference's pip forms — list of requirements, or
    {"packages": [...], "find_links": path} — normalized for this
    environment's OFFLINE install contract: pip always runs --no-index
    against a local wheel cache (find_links; default $CA_PIP_FIND_LINKS),
    mirroring _private/runtime_env/pip.py minus the network."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict) or not spec.get("packages"):
        raise ValueError(
            'runtime_env "pip" must be a list of requirements or '
            '{"packages": [...], "find_links": <local wheel dir>}'
        )
    pkgs = [str(p) for p in spec["packages"]]
    find_links = spec.get("find_links") or os.environ.get("CA_PIP_FIND_LINKS")
    if not find_links:
        raise ValueError(
            "offline pip installs need a local wheel cache: pass "
            '{"pip": {"packages": [...], "find_links": "/path/to/wheels"}} '
            "or set CA_PIP_FIND_LINKS"
        )
    return {"packages": pkgs, "find_links": os.path.abspath(find_links)}


def pip_env_hash(norm: Dict[str, Any]) -> str:
    """URI-cache key (uri_cache.py analogue): the env is content-addressed
    by its normalized spec, so identical specs share one installed dir."""
    blob = "\x00".join(sorted(norm["packages"])) + "\x01" + norm["find_links"]
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _zip_dir(path: str, excludes: Optional[List[str]] = None) -> bytes:
    buf = io.BytesIO()
    excludes = excludes or []
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                if any(rel.startswith(e) for e in excludes):
                    continue
                z.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_PKG_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes (max {_MAX_PKG_BYTES})"
        )
    return data


def _upload_dir(worker, path: str, excludes=None) -> str:
    """Zip + store in head KV under content digest; returns the digest."""
    data = _zip_dir(os.path.abspath(path), excludes)
    digest = hashlib.sha256(data).hexdigest()[:24]
    # overwrite=False: content-addressed, first writer wins
    worker.head_call("kv_put", ns=_PKG_NS, key=digest, value=data, overwrite=False)
    return digest


def prepare(runtime_env: Optional[Dict[str, Any]], worker) -> Optional[Dict[str, Any]]:
    """Driver side: turn user runtime_env into its wire form."""
    if not runtime_env:
        return None
    validate(runtime_env)
    wire: Dict[str, Any] = {}
    if runtime_env.get("env_vars"):
        wire["env_vars"] = dict(runtime_env["env_vars"])
    wd = runtime_env.get("working_dir")
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        excludes = (runtime_env.get("config") or {}).get("excludes")
        wire["working_dir_pkg"] = _upload_dir(worker, wd, excludes)
    mods = runtime_env.get("py_modules")
    if mods:
        pkgs = []
        for m in mods:
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a directory")
            pkgs.append((os.path.basename(os.path.abspath(m)), _upload_dir(worker, m)))
        wire["py_module_pkgs"] = pkgs
    if runtime_env.get("pip"):
        norm = normalize_pip_spec(runtime_env["pip"])
        if not os.path.isdir(norm["find_links"]):
            raise ValueError(f"pip find_links {norm['find_links']!r} is not a directory")
        norm["hash"] = pip_env_hash(norm)
        wire["pip"] = norm
    return wire or None


class RuntimeEnvContext:
    """Worker side: materialize and (optionally) roll back a runtime env."""

    def __init__(self, wire: Dict[str, Any], worker):
        self.wire = wire or {}
        self.worker = worker
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._added_paths: List[str] = []

    def _materialize_pkg(self, digest: str) -> str:
        cache_root = os.path.join(self.worker.session_dir, "runtime_env_cache")
        dest = os.path.join(cache_root, digest)
        if os.path.isdir(dest):
            return dest
        reply = self.worker.head_call("kv_get", ns=_PKG_NS, key=digest)
        data = reply.get("value")
        if data is None:
            raise RuntimeError(f"runtime_env package {digest} missing from cluster KV")
        os.makedirs(cache_root, exist_ok=True)
        # pid alone is not unique: two executor threads in one worker (actor
        # max_concurrency / concurrency groups) applying the same spec would
        # interleave into a shared tmp and poison the cache for the session
        tmp = dest + f".tmp{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # concurrent extract won
        return dest

    def _materialize_pip(self, norm: Dict[str, Any]) -> str:
        """Install the pip env into a spec-hash-keyed cache dir (once per
        session per spec) and return it.  Strictly offline: --no-index with
        the given local wheel cache.  Installs land in a tmp dir renamed
        atomically, so concurrent workers race safely and a crashed install
        never half-populates the cache."""
        import subprocess

        dest = os.path.join(
            self.worker.session_dir, "runtime_env_cache", "pip_" + norm["hash"]
        )
        if os.path.isdir(dest):
            return dest
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = dest + f".tmp{os.getpid()}.{uuid.uuid4().hex[:8]}"
        cmd = [
            sys.executable, "-m", "pip", "install", "--quiet",
            "--no-index", "--find-links", norm["find_links"],
            "--target", tmp, "--no-warn-script-location",
            *norm["packages"],
        ]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        except subprocess.TimeoutExpired:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"offline pip install failed ({' '.join(norm['packages'])}): "
                f"timed out after 300s"
            )
        if r.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"offline pip install failed ({' '.join(norm['packages'])}): "
                f"{r.stderr.strip()[-500:]}"
            )
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # concurrent install won
        return dest

    def apply(self):
        for k, v in (self.wire.get("env_vars") or {}).items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        pip_spec = self.wire.get("pip")
        if pip_spec:
            path = self._materialize_pip(pip_spec)
            sys.path.insert(0, path)
            self._added_paths.append(path)
        pkg = self.wire.get("working_dir_pkg")
        if pkg:
            path = self._materialize_pkg(pkg)
            self._saved_cwd = os.getcwd()
            os.chdir(path)
            sys.path.insert(0, path)
            self._added_paths.append(path)
        for _name, digest in self.wire.get("py_module_pkgs") or []:
            path = self._materialize_pkg(digest)
            # the zip contains the module dir's *contents*; import must see the
            # module by name, so expose the parent with a named symlink
            parent = path + "_mods"
            os.makedirs(parent, exist_ok=True)
            link = os.path.join(parent, _name)
            if not os.path.exists(link):
                try:
                    os.symlink(path, link)
                except FileExistsError:
                    pass
            sys.path.insert(0, parent)
            self._added_paths.append(parent)

    def restore(self):
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved_env.clear()
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
            self._saved_cwd = None
        for p in self._added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
            # pool workers are reused: a module cached in sys.modules would
            # leak this env's code into later tasks even after the path is
            # gone, so evict everything imported from under the env dir —
            # including namespace packages, whose __file__ is None but whose
            # __path__ points into it
            prefix = p + os.sep
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and (f.startswith(prefix) or f == p):
                    del sys.modules[name]
                    continue
                try:
                    paths = list(getattr(mod, "__path__", None) or [])
                except Exception:
                    continue
                if any(x == p or str(x).startswith(prefix) for x in paths):
                    del sys.modules[name]
        self._added_paths.clear()

    def __enter__(self):
        self.apply()
        return self

    def __exit__(self, *exc):
        self.restore()
        return False
