"""@remote function machinery (analogue of python/ray/remote_function.py).

`@remote` wraps a function into a RemoteFunction whose `.remote(*args)`
submits a task and returns ObjectRef(s).  `.options(...)` returns a shallow
override, like the reference's options resolution
(python/ray/_private/ray_option_utils.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Union

from .object_ref import ObjectRef
from .worker import global_worker

_VALID_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "resources",
    "num_returns",
    "max_retries",
    "retry_exceptions",
    "name",
    "placement_group",
    "placement_group_bundle_index",
    "scheduling_strategy",
    "runtime_env",
}


def _check_options(opts: Dict[str, Any]):
    unknown = set(opts) - _VALID_OPTIONS
    if unknown:
        raise ValueError(f"unknown option(s): {sorted(unknown)}")
    nr = opts.get("num_returns")
    if nr is not None and nr != "streaming" and (not isinstance(nr, int) or nr < 1):
        raise ValueError('num_returns must be a positive int or "streaming"')
    nt = opts.get("num_tpus")
    if nt:
        from .accelerators import validate_chip_request

        validate_chip_request(float(nt))


def _normalize_pg(opts: Dict[str, Any]) -> Dict[str, Any]:
    """Accept PlacementGroup objects or scheduling strategies in options."""
    from .placement import PlacementGroup
    from .scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
        SpreadSchedulingStrategy,
    )

    out = dict(opts)
    strat = out.pop("scheduling_strategy", None)
    if isinstance(strat, PlacementGroupSchedulingStrategy):
        out["placement_group"] = strat.placement_group
        out["placement_group_bundle_index"] = strat.placement_group_bundle_index
    elif isinstance(strat, NodeAffinitySchedulingStrategy):
        out["strategy"] = {
            "type": "NODE_AFFINITY",
            "node_id": strat.node_id,
            "soft": strat.soft,
        }
    elif isinstance(strat, NodeLabelSchedulingStrategy):
        out["strategy"] = strat.to_wire()
    elif isinstance(strat, SpreadSchedulingStrategy) or strat == "SPREAD":
        out["strategy"] = {"type": "SPREAD"}
    pg = out.get("placement_group")
    if isinstance(pg, PlacementGroup):
        out["placement_group"] = pg.id.hex()
    if out.get("placement_group") is not None:
        out.setdefault("placement_group_bundle_index", 0)
    return out


class RemoteFunction:
    def __init__(self, fn, default_options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._default_options = default_options or {}
        _check_options(self._default_options)
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        return self._remote(args, kwargs, self._default_options)

    def options(self, **opts) -> "RemoteFunction":
        _check_options(opts)
        merged = {**self._default_options, **opts}
        return RemoteFunction(self._function, merged)

    def _remote(self, args, kwargs, opts):
        w = global_worker()
        if opts.get("num_returns") == "streaming":
            return w.submit_streaming_task(self._function, args, kwargs, _normalize_pg(opts))
        refs = w.submit_task(self._function, args, kwargs, _normalize_pg(opts))
        return refs[0] if opts.get("num_returns", 1) == 1 else refs

    def bind(self, *args, **kwargs):
        """Create a task DAG node (reference: dag/function_node.py)."""
        from ..dag.node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__!r} cannot be called directly; "
            f"use .remote()"
        )

    @property
    def underlying(self):
        return self._function
