"""Streaming generator returns (analogue of the reference's
ObjectRefGenerator, python/ray/_raylet.pyx:284, with producer-side
backpressure per src/ray/core_worker/generator_waiter.h).

A task or actor method submitted with num_returns="streaming" returns an
ObjectRefGenerator.  The executing worker streams each yielded item to the
submitter as it is produced ("stream_item" frames over the direct task
socket; items use the normal inline/shm result packaging), and the original
RPC reply doubles as the end-of-stream marker.  The producer BLOCKS once
more than `streaming_backpressure` items are unconsumed; the consumer acks
as it takes refs off the generator.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .ids import ObjectID, TaskID


class StreamState:
    """Submitter-side state of one in-flight streaming task."""

    __slots__ = (
        "task_id", "addr", "produced", "next_read", "ended", "error", "cond",
    )

    def __init__(self, task_id: TaskID, addr: Optional[str] = None):
        self.task_id = task_id
        self.addr = addr  # executing worker (ack target), set at push time
        self.produced = 0
        self.next_read = 0
        self.ended = False
        self.error: Optional[BaseException] = None
        self.cond = threading.Condition()

    def on_item(self, idx: int):
        with self.cond:
            self.produced = max(self.produced, idx + 1)
            self.cond.notify_all()

    def on_end(self, error: Optional[BaseException] = None):
        with self.cond:
            self.ended = True
            self.error = error
            self.cond.notify_all()


class ObjectRefGenerator:
    """Iterator of ObjectRefs for a streaming task's yields.

    next() blocks until the next item has been produced (or the stream
    ended), returns its ObjectRef, and acks consumption so the producer's
    backpressure window advances.  The refs resolve through the normal
    get() machinery.
    """

    def __init__(self, worker, state: StreamState, owner: str):
        self._worker = worker
        self._state = state
        self._owner = owner

    def __iter__(self):
        return self

    def __next__(self):
        st = self._state
        with st.cond:
            while st.next_read >= st.produced and not st.ended:
                st.cond.wait()
            if st.next_read < st.produced:
                idx = st.next_read
                st.next_read += 1
            else:
                if st.error is not None:
                    raise st.error
                raise StopIteration
        self._worker.stream_ack(st)
        from .object_ref import ObjectRef

        oid = ObjectID.for_return(st.task_id, idx)
        return ObjectRef(oid, owner=self._owner, worker=self._worker)

    def completed(self) -> bool:
        with self._state.cond:
            return self._state.ended and self._state.next_read >= self._state.produced

    def cancel(self) -> None:
        """Abandon the stream: interrupt the producer (TaskCancelledError at
        its next bytecode boundary, the normal ca.cancel path) and end the
        local stream so blocked __next__ callers wake with the error.  A
        consumer that stops reading mid-stream MUST call this — otherwise
        the producer keeps generating until its backpressure window fills
        (serve SSE client-disconnect path).  Idempotent; safe from any
        thread."""
        from ..core.errors import TaskCancelledError

        st = self._state
        with st.cond:
            already = st.ended
        if not already:
            self._worker.cancel_stream(st)
            st.on_end(TaskCancelledError("stream abandoned by consumer"))
