"""Network-chaos plane: deterministic per-link fault injection for the frame
protocol (the partition/straggler analogue of RpcChaos, which only models an
RPC that errors CLEANLY).

Real control planes die to the failures RpcChaos cannot express: a network
partition where frames vanish and connections HANG instead of erroring, a
straggling link that delivers every frame late, a flapping cross-zone hop.
This module injects exactly those, per directed link (src-node, dst-node),
from a seeded schedule — the same seed and spec always produce the same
event sequence, so a chaos failure replays.

Policies live in a spec string (config.testing_net_chaos / the
CA_TESTING_NET_CHAOS env var, installed at process start; `ca chaos set`
broadcasts one cluster-wide at runtime through the head):

    seed=7;epoch=1722.5;n0<>node1:blackhole@1.0+8.0;n0>node2:delay=0.05

Clauses (`;`-separated):
  seed=N              deterministic schedule seed (default 0)
  epoch=FLOAT         wall-clock anchor for window offsets; every process
                      given the same epoch agrees on when windows open even
                      though they installed the spec at different times
                      (default: install time — fine for one-process tests)
  SRC>DST:actions     one directed link; SRC<>DST installs both directions
with comma-separated actions:
  blackhole           drop every frame, forever
  blackhole@S+D       drop frames in the window [S, S+D) seconds from epoch
  delay=SEC           per-frame latency (straggler link; ordering preserved)
  jitter=SEC          extra per-frame latency in [0, SEC), drawn from the
                      seeded per-link stream
  flap=UP/DOWN[@S]    from S (default 0) the link alternates up ~UP s /
                      down ~DOWN s; each phase length is drawn from the
                      seeded per-link stream in [0.5x, 1.5x) of nominal

Injection points (all gated on `NET_CHAOS is None` — one module-global load
per flush/dial when disabled, zero per-frame work):
  - protocol._Cork.flush: frames to a blackholed/flap-down peer are silently
    dropped (the connection stays open and HANGS — partitions don't error);
    delay/jitter defer the transport write, FIFO per connection
  - Connection._read_loop / Server._on_client: frames RECEIVED from a
    partitioned peer are dropped too, so one chaos-enabled process can
    simulate a symmetric partition against peers that never installed a spec
  - util.aio.dial: dialing a blackholed peer hangs until the dial timeout
    (SYN into the void), healing mid-wait if the schedule says so
  - protocol.fence_close: a transport close toward a blackholed peer is
    DEFERRED until the link heals — a real partition does not deliver FIN,
    so the far side must discover its death verdict at heal time, not get
    tipped off by an impossible EOF

Link identity: each process declares its own node (set_local_node) and
labels outgoing connections with the peer's node where it knows it (dials to
the head are "n0"; the head labels agent/worker dials and its server-side
registration writers; submitters label lease-grant worker connections from
the lease directory).  Unlabeled connections are never touched.
"""

from __future__ import annotations

import bisect
import random
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

# fast-path gate: None = chaos disabled, every hook bypasses in one check
NET_CHAOS: Optional["NetworkChaos"] = None

# this process's node id (link source for outgoing frames)
_local_node: str = "n0"

# known peer addresses -> node ids (fallback labeling for dials)
_addr_nodes: Dict[str, str] = {}

# outgoing writer -> peer node id (weak: dies with the transport)
_writer_links: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def set_local_node(node_id: str) -> None:
    global _local_node
    if node_id:
        _local_node = node_id


def local_node() -> str:
    return _local_node


_ADDR_NODES_CAP = 4096  # drop-oldest bound: worker churn must not leak


def register_addr(addr: Optional[str], node_id: Optional[str]) -> None:
    """Remember which node serves `addr` (labels future dials to it).
    Bounded drop-oldest: a long-lived process churning through short-lived
    worker addresses keeps at most the most recent _ADDR_NODES_CAP entries
    (an evicted live address just loses its chaos label, never breaks)."""
    if addr and node_id:
        _addr_nodes[addr] = node_id
        while len(_addr_nodes) > _ADDR_NODES_CAP:
            del _addr_nodes[next(iter(_addr_nodes))]


def node_for_addr(addr: Optional[str]) -> Optional[str]:
    return _addr_nodes.get(addr) if addr else None


def label_writer(writer, dst_node: Optional[str]) -> None:
    """Tag a transport with its peer's node id; chaos decisions for frames
    on this writer use the (local_node, dst_node) link policy."""
    if writer is not None and dst_node:
        _writer_links[writer] = dst_node


def link_of(writer) -> Optional[str]:
    try:
        return _writer_links.get(writer)
    except TypeError:
        return None


class LinkPolicy:
    __slots__ = (
        "src", "dst", "delay_s", "jitter_s", "bh_start", "bh_end",
        "flap_up", "flap_down", "flap_start",
    )

    def __init__(self, src: str, dst: str):
        self.src = src
        self.dst = dst
        self.delay_s = 0.0
        self.jitter_s = 0.0
        self.bh_start: Optional[float] = None  # None = no blackhole
        self.bh_end = float("inf")
        self.flap_up = 0.0  # 0 = no flap
        self.flap_down = 0.0
        self.flap_start = 0.0


def _parse_action(pol: LinkPolicy, act: str) -> None:
    act = act.strip()
    if not act:
        return
    if act == "blackhole":
        pol.bh_start, pol.bh_end = 0.0, float("inf")
    elif act.startswith("blackhole@"):
        start, _, dur = act[len("blackhole@"):].partition("+")
        pol.bh_start = float(start)
        pol.bh_end = pol.bh_start + float(dur) if dur else float("inf")
    elif act.startswith("delay="):
        pol.delay_s = float(act[len("delay="):])
    elif act.startswith("jitter="):
        pol.jitter_s = float(act[len("jitter="):])
    elif act.startswith("flap="):
        body = act[len("flap="):]
        body, _, start = body.partition("@")
        up, _, down = body.partition("/")
        pol.flap_up = float(up)
        pol.flap_down = float(down or up)
        pol.flap_start = float(start) if start else 0.0
        if pol.flap_up <= 0 or pol.flap_down <= 0:
            raise ValueError(f"flap phases must be positive: {act!r}")
    else:
        raise ValueError(
            f"unknown net-chaos action {act!r} (want blackhole[@S+D], "
            f"delay=SEC, jitter=SEC, flap=UP/DOWN[@S])"
        )


class NetworkChaos:
    """Parsed spec + seeded schedules + decision entry points.

    Deterministic by construction: flap phase lengths and per-frame jitter
    come from per-link `random.Random` streams seeded by (seed, src, dst),
    and every window is an offset from one shared epoch — two instances
    built from the same spec produce identical schedules and identical
    per-frame decision sequences (asserted in tests/test_partition.py).
    """

    def __init__(self, spec: str, local: Optional[str] = None, now: Optional[float] = None):
        self.spec = spec
        self.seed = 0
        self.epoch = now if now is not None else time.time()
        self.local = local or _local_node
        self.policies: Dict[Tuple[str, str], LinkPolicy] = {}
        self.stats: Dict[str, int] = {
            "frames_dropped": 0,
            "frames_delayed": 0,
            "recv_dropped": 0,
            "dials_blocked": 0,
            "closes_deferred": 0,
        }
        self.events: deque = deque(maxlen=4096)
        links: List[Tuple[str, str, str]] = []
        for clause in filter(None, (c.strip() for c in (spec or "").split(";"))):
            if clause.startswith("seed="):
                self.seed = int(clause[len("seed="):])
            elif clause.startswith("epoch="):
                self.epoch = float(clause[len("epoch="):])
            else:
                link, sep, actions = clause.partition(":")
                if not sep:
                    raise ValueError(f"bad net-chaos clause {clause!r}")
                if "<>" in link:
                    a, b = link.split("<>", 1)
                    links.append((a.strip(), b.strip(), actions))
                    links.append((b.strip(), a.strip(), actions))
                elif ">" in link:
                    a, b = link.split(">", 1)
                    links.append((a.strip(), b.strip(), actions))
                else:
                    raise ValueError(
                        f"bad net-chaos link {link!r} (want SRC>DST or SRC<>DST)"
                    )
        for src, dst, actions in links:
            pol = self.policies.setdefault((src, dst), LinkPolicy(src, dst))
            for act in actions.split(","):
                _parse_action(pol, act)
        # seeded per-link streams: flap timelines are extended lazily but
        # deterministically; frame jitter draws consume the frame stream
        self._flap_toggles: Dict[Tuple[str, str], List[float]] = {}
        self._frame_rngs: Dict[Tuple[str, str], random.Random] = {}
        # last observed up/down state per link, so transitions land in the
        # event log exactly once (observation timing doesn't change the
        # SCHEDULE, which is what determinism tests assert)
        self._last_state: Dict[Tuple[str, str], bool] = {}

    # ------------------------------------------------------------- schedule
    def _link_rng(self, key: Tuple[str, str], stream: str) -> random.Random:
        return random.Random(f"{self.seed}:{stream}:{key[0]}>{key[1]}")

    def _toggles(self, key: Tuple[str, str], until: float) -> List[float]:
        """Flap toggle offsets [down0, up0, down1, up1, ...] extended (from
        the seeded stream, so extension is deterministic) to cover `until`."""
        pol = self.policies[key]
        tl = self._flap_toggles.get(key)
        if tl is None:
            tl = self._flap_toggles[key] = [pol.flap_start]
        # phase lengths depend only on their index, never on how far a
        # previous call extended the list — interleaved queries on the same
        # link therefore cannot perturb the schedule
        while tl[-1] <= until:
            i = len(tl)
            rng = self._link_rng(key, f"flapn:{i}")
            nominal = pol.flap_down if i % 2 == 1 else pol.flap_up
            tl.append(tl[-1] + nominal * (0.5 + rng.random()))
        return tl

    def flap_schedule(self, src: str, dst: str, horizon_s: float) -> List[Tuple[str, float]]:
        """The link's up/down transition schedule out to `horizon_s`
        (offsets from epoch) — pure function of (spec, seed)."""
        key = (src, dst)
        pol = self.policies.get(key)
        if pol is None or not pol.flap_up:
            return []
        tl = self._toggles(key, horizon_s)
        out = []
        for i, t in enumerate(tl):
            if t > horizon_s:
                break
            out.append(("down" if i % 2 == 0 else "up", round(t, 6)))
        return out

    # ------------------------------------------------------------ decisions
    def t_rel(self, now: Optional[float] = None) -> float:
        return (time.time() if now is None else now) - self.epoch

    def link_down(self, src: Optional[str], dst: Optional[str], now: Optional[float] = None) -> bool:
        if src is None or dst is None:
            return False
        key = (src, dst)
        pol = self.policies.get(key)
        if pol is None:
            return False
        t = self.t_rel(now)
        down = False
        if pol.bh_start is not None and pol.bh_start <= t < pol.bh_end:
            down = True
        elif pol.flap_up and t >= pol.flap_start:
            tl = self._toggles(key, t)
            # odd toggle count passed -> inside a DOWN phase (the schedule
            # starts with a down phase at flap_start)
            down = bisect.bisect_right(tl, t) % 2 == 1
        prev = self._last_state.get(key)
        if prev != down:
            self._last_state[key] = down
            self.events.append(
                ("down" if down else "up", src, dst, round(t, 3))
            )
            # flight recorder: the schedule firing, with the seed so a chaos
            # incident timeline can be replayed from the journal alone
            from ..util import flightrec

            if flightrec.REC is not None:
                flightrec.REC.record(
                    "chaos", "link_down" if down else "link_up",
                    src=src, dst=dst, t_rel=round(t, 3),
                    seed=self.seed, spec=self.spec,
                )
        return down

    def frame_delay(self, src: Optional[str], dst: Optional[str]) -> float:
        if src is None or dst is None:
            return 0.0
        pol = self.policies.get((src, dst))
        if pol is None:
            return 0.0
        d = pol.delay_s
        if pol.jitter_s:
            rng = self._frame_rngs.get((src, dst))
            if rng is None:
                rng = self._frame_rngs[(src, dst)] = self._link_rng(
                    (src, dst), "frames"
                )
            d += rng.random() * pol.jitter_s
        return d

    def count(self, stat: str, n: int = 1) -> None:
        self.stats[stat] = self.stats.get(stat, 0) + n


# ---------------------------------------------------------------- lifecycle
def install(spec: str, local_node_id: Optional[str] = None,
            epoch: Optional[float] = None) -> Optional[NetworkChaos]:
    """Parse and activate a spec in THIS process (empty spec deactivates).
    Raises ValueError on a malformed spec — a typo'd chaos schedule that
    silently injects nothing would invalidate the test relying on it."""
    global NET_CHAOS
    if local_node_id:
        set_local_node(local_node_id)
    if not (spec or "").strip():
        NET_CHAOS = None
        return None
    NET_CHAOS = NetworkChaos(spec, local=_local_node, now=epoch)
    return NET_CHAOS


def clear() -> None:
    global NET_CHAOS
    NET_CHAOS = None


def maybe_install_from_config(config, local_node_id: Optional[str] = None) -> None:
    """Process-start installation from config.testing_net_chaos (the
    CA_TESTING_NET_CHAOS env override rides the same field)."""
    if local_node_id:
        set_local_node(local_node_id)
    spec = getattr(config, "testing_net_chaos", "") or ""
    if spec.strip():
        install(spec, local_node_id)


def status() -> dict:
    ch = NET_CHAOS
    if ch is None:
        return {"active": False}
    return {
        "active": True,
        "spec": ch.spec,
        "seed": ch.seed,
        "epoch": ch.epoch,
        "local": ch.local,
        "links": [f"{s}>{d}" for (s, d) in ch.policies],
        "stats": dict(ch.stats),
        "events": list(ch.events)[-50:],
    }
