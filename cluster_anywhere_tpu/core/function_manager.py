"""Function/actor-class export and caching.

The reference exports pickled function definitions once to GCS KV and workers
import them on first use (python/ray/_private/function_manager.py,
gcs_function_manager.h).  Same here: definitions are content-addressed by
sha256 of the cloudpickle blob, uploaded to the head KV once per driver, and
cached per worker process.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle


class FunctionManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_id: Dict[bytes, Any] = {}  # fn_id -> callable / class
        self._exported: set = set()  # fn_ids known to be in head KV
        self._blob_cache: Dict[int, Tuple[bytes, bytes]] = {}  # id(obj) -> (fn_id, blob)
        self._blob_by_id: Dict[bytes, bytes] = {}  # fn_id -> blob (exporter side)

    def export(self, obj: Any) -> Tuple[bytes, Optional[bytes]]:
        """Returns (fn_id, blob_to_upload_or_None_if_already_exported)."""
        key = id(obj)
        with self._lock:
            cached = self._blob_cache.get(key)
            if cached is not None:
                fn_id, blob = cached
                return fn_id, (None if fn_id in self._exported else blob)
        blob = cloudpickle.dumps(obj)
        fn_id = hashlib.sha256(blob).digest()[:16]
        with self._lock:
            self._blob_cache[key] = (fn_id, blob)
            self._blob_by_id[fn_id] = blob
            self._by_id[fn_id] = obj
            if fn_id in self._exported:
                return fn_id, None
            return fn_id, blob

    def blob_for(self, fn_id: bytes) -> Optional[bytes]:
        """The exported blob of a function THIS process exported (None for
        functions learned by id only).  Lets a submitter inline the
        definition into a task push while the head — the normal blob
        directory — is down (lease-plane grants keep flowing through head
        restarts, so pushes must not depend on head-served blobs)."""
        with self._lock:
            return self._blob_by_id.get(fn_id)

    def mark_exported(self, fn_id: bytes):
        with self._lock:
            self._exported.add(fn_id)

    def get(self, fn_id: bytes) -> Optional[Any]:
        with self._lock:
            return self._by_id.get(fn_id)

    def load(self, fn_id: bytes, blob: bytes) -> Any:
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._by_id[fn_id] = obj
        return obj
