"""Wire protocol between processes: length-prefixed msgpack frames over unix
domain sockets (same host) or TCP (cross host).

This is the analogue of the reference's gRPC services + local-socket
flatbuffer protocol (src/ray/protobuf/*.proto, src/ray/raylet/format/): a
small set of typed messages between driver <-> head <-> node agents <->
workers.  msgpack maps keep the schema explicit and language-neutral so the
head can later be swapped for the C++ implementation without changing clients.

Addresses are strings with a scheme prefix: "unix:/path/to.sock" or
"tcp:host:port"; a bare path is treated as unix for backward compatibility.
A Server can listen on several addresses at once (unix for same-host clients,
TCP for the rest of the cluster) and shares one handler across them.

Frame format: [u32 big-endian length][msgpack map]
Every request carries "m" (method), "i" (request id); responses echo "i" and
carry "ok" plus method-specific fields, or "err" with a pickled exception.

Batching: logical messages corked during one event-loop iteration are packed
into ONE physical frame — a `batch` envelope {"m": "batch", "b": [msg, ...]}
— so a 4000-call burst pays dozens of frame/encode/dispatch cycles instead of
4000.  Receivers (Connection read loop, Server dispatch, BlockingClient)
transparently expand envelopes back into logical messages; chaos budgets and
per-method stats count LOGICAL messages, never physical frames.

Lease plane: the node-local lease granting subsystem rides this same frame
protocol and its batch envelopes.  Head -> agent: `lease_block` (delegate
workers into a block), `lease_block_revoke` (reclaim unleased slots).
Agent -> head: `lease_block_return` (returned slots), plus per-pool
`lease_stats` piggybacked on `node_heartbeat`.  Submitter -> agent:
`lease_grant` / `lease_release` — the hot lease class, which therefore
never crosses the head's loop in steady state.  Submitter -> head:
`request_lease` may carry `ttl` (escalation probe; the head replies
{"expired": true} past it), and `push_task` may carry `fn_blob` (function
definition inlined while the head — the blob directory — is down).  All of
these are ordinary logical messages: they cork, batch, and charge chaos
budgets exactly like every other method.

Log plane: the structured log pipeline rides the same frames and envelopes.
Agent -> head: `log_batch` (notify; a tick's tailed records from that node's
capture files).  Driver -> head: `log_sub` (notify; join/leave the cluster
log stream) and `log_fetch` (request; resolve a worker/actor/task/node id
and read/tail its log, proxied cross-node).  Head -> agent: `log_read`
(request; tail a file in the agent's node dir).  Head -> driver: `log_batch`
pushes (unsolicited frames, expanded by the Connection push handler).  All
of them cork and batch like any other logical message; delivery to a stalled
subscriber drops (bounded buffers + a dropped-line counter) rather than
backpressuring the printing worker.

Trace context: logical task/actor-call messages may carry a small optional
`tr` field (TRACE_FIELD) — {"tid": trace id, "sid": parent span id} — minted
at remote() submission when util/tracing is enabled.  Batch envelopes splice
already-encoded whole message bodies, so the field survives corking/batching
untouched; receivers read it off the logical message like any other field.
Disabled tracing sends nothing (no field, no bytes).

A deterministic fault-injection hook mirrors the reference's RPC chaos
(src/ray/rpc/rpc_chaos.h): CA_TESTING_RPC_FAILURE="method=N,method2=M" makes
the first N sends of `method` raise ConnectionError before the write.  The
budget is charged at call()/call_cb()/notify() time — one logical message,
one decrement — so injected failures keep their meaning whether the survivors
travel as single frames or inside a batch envelope.
"""

from __future__ import annotations

import asyncio
import itertools
import socket as _socket
import struct
import weakref
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from . import netchaos
from .config import get_config

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 31

# optional trace-context field on logical task/actor-call messages (see
# util/tracing.py); single definition so submit and execute sides agree
TRACE_FIELD = "tr"

# Per-process wire counters (control-plane amortization observability).
# Plain ints in a module dict: incremented on hot paths, so no locks — the
# asyncio loop owns sends/recvs, and the metrics flusher only reads.
WIRE_STATS: Dict[str, int] = {
    "frames_sent": 0,        # physical frames written
    "messages_sent": 0,      # logical messages written
    "batch_frames_sent": 0,  # physical frames that were batch envelopes
    "frames_recv": 0,        # physical frames read
    "messages_recv": 0,      # logical messages read
    "template_renders": 0,   # task-spec template fast-path encodes
    "refcount_flushes_suppressed": 0,  # obj_refs sends merged away (worker.py)
}


def wire_stats() -> Dict[str, int]:
    """Snapshot of this process's wire counters."""
    return dict(WIRE_STATS)

# The event loop holds only weak references to tasks; anything fire-and-forget
# must be pinned here or it can be garbage-collected mid-execution (observed:
# silently vanishing task submissions under load).
_background_tasks: set = set()


def spawn_bg(coro) -> asyncio.Task:
    task = asyncio.ensure_future(coro)
    _background_tasks.add(task)
    task.add_done_callback(_background_tasks.discard)
    return task


class RpcChaos:
    """Counts down per-method failure budgets from config.testing_rpc_failure
    and holds per-method latency injections from config.testing_rpc_delay
    ("method=MS" pairs: every matching send waits MS milliseconds first —
    the straggler-RPC knob, where the failure knob models clean errors).

    Method names in BOTH specs are validated against the generated RPC
    contract (docs/PROTOCOL_CONTRACT.json, `ca lint --contract`) at parse
    time: a typo'd method in a chaos spec used to simply never fire — the
    test "passed" while injecting nothing.  Unknown names raise immediately.
    """

    def __init__(self, spec: str, delay_spec: str = ""):
        self._budget: Dict[str, int] = {}
        for part in filter(None, (spec or "").split(",")):
            method, _, n = part.partition("=")
            self._budget[method.strip()] = int(n or 1)
        self._delay: Dict[str, float] = {}
        for part in filter(None, (delay_spec or "").split(",")):
            method, _, ms = part.partition("=")
            self._delay[method.strip()] = float(ms or 0.0) / 1000.0
        if self._budget or self._delay:
            self._validate_methods()

    def _validate_methods(self) -> None:
        from ..analysis.contract import load_contract  # lazy: cold path only

        doc = load_contract()
        if doc is None:
            return  # no checked-out contract (installed package): best effort
        known = set(doc.get("methods") or ())
        if not known:
            return
        unknown = sorted((set(self._budget) | set(self._delay)) - known)
        if unknown:
            raise ValueError(
                f"CA_TESTING_RPC_FAILURE/CA_TESTING_RPC_DELAY name unknown "
                f"RPC method(s) {unknown}: not in the extracted protocol "
                f"contract ({len(known)} methods; regenerate with `ca lint "
                f"--contract` if the protocol changed)"
            )

    def maybe_fail(self, method: str):
        left = self._budget.get(method)
        if left:
            self._budget[method] = left - 1
            raise ConnectionError(f"[chaos] injected RPC failure for {method}")

    def delay_s(self, method: str) -> float:
        """Injected pre-send latency for `method` (0.0 = none)."""
        return self._delay.get(method, 0.0) if self._delay else 0.0


_chaos: Optional[RpcChaos] = None


def rpc_chaos() -> RpcChaos:
    global _chaos
    if _chaos is None:
        cfg = get_config()
        _chaos = RpcChaos(
            cfg.testing_rpc_failure, getattr(cfg, "testing_rpc_delay", "")
        )
    return _chaos


def reset_rpc_chaos(spec: str = "", delay_spec: str = ""):
    global _chaos
    _chaos = RpcChaos(spec, delay_spec)


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    """Read one length-prefixed frame; None on clean EOF.

    Deliberately unbounded: every caller is a persistent-connection read
    loop (Connection._read_loop, Server._on_client) where waiting forever
    for the NEXT frame is the correct idle state.  Request/response
    contexts that must not trust the peer use util.aio.read_frame, which
    bounds this with config.io_timeout_s."""
    try:
        # ca-lint: ignore[async-unbounded-io] — persistent read loop (see docstring)
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    # body follows the header immediately; a peer that sent 4 length bytes
    # and then stalls is torn down by the health plane, not a per-read timer
    # ca-lint: ignore[async-unbounded-io]
    body = await reader.readexactly(length)
    msg = msgpack.unpackb(body, raw=False, strict_map_key=False)
    WIRE_STATS["frames_recv"] += 1
    if msg.get("m") == "batch":
        WIRE_STATS["messages_recv"] += len(msg.get("b") or ())
    else:
        WIRE_STATS["messages_recv"] += 1
    return msg


def iter_messages(msg: dict):
    """Expand a frame into its logical messages (identity for plain frames)."""
    if msg.get("m") == "batch":
        return msg.get("b") or ()
    return (msg,)


# batch envelope, built by hand so already-encoded message bodies can be
# spliced in without a decode/re-encode round trip:
#   map{ "m": "batch", "b": [ <body>, <body>, ... ] }
_BATCH_PREFIX = (
    b"\x82"
    + msgpack.packb("m", use_bin_type=True)
    + msgpack.packb("batch", use_bin_type=True)
    + msgpack.packb("b", use_bin_type=True)
)


def _array_header(n: int) -> bytes:
    if n < 16:
        return bytes((0x90 | n,))
    if n < 1 << 16:
        return b"\xdc" + n.to_bytes(2, "big")
    return b"\xdd" + n.to_bytes(4, "big")


# one envelope never exceeds this payload size: keeps a flood of large
# messages (object chunks, collective pushes) from assembling frames near the
# MAX_FRAME limit, and bounds the receiver's single-unpack working set
_BATCH_BYTES_CAP = 32 << 20


class _Cork:
    """Per-writer message batcher: logical messages queued during one
    event-loop iteration are packed into a single `batch` envelope frame and
    one transport write — one frame header, one receiver unpack, one send
    syscall for the whole tick's traffic (the dominant costs of high-rate
    task/actor fan-out on few cores).  A lone message goes out as a plain
    frame.  Latency cost is at most one loop callback."""

    __slots__ = ("writer", "bodies", "scheduled", "_next_due")

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.bodies: list = []  # encoded msgpack map bodies (no length prefix)
        self.scheduled = False
        self._next_due = 0.0  # delayed-emission FIFO watermark (net chaos)

    def write_body(self, body: bytes):
        self.bodies.append(body)
        if not self.scheduled:
            self.scheduled = True
            asyncio.get_running_loop().call_soon(self.flush)

    def flush(self):
        self.scheduled = False
        if not self.bodies:
            return
        bodies = self.bodies
        self.bodies = []
        # network-chaos send filter (one module-global check when disabled):
        # frames to a blackholed/flap-down peer vanish silently — the
        # connection stays open and callers HANG, which is what a real
        # partition does; a delayed link defers the transport write instead
        chaos_delay = 0.0
        ch = netchaos.NET_CHAOS
        if ch is not None:
            dst = netchaos.link_of(self.writer)
            if dst is not None:
                if ch.link_down(ch.local, dst):
                    ch.count("frames_dropped", len(bodies))
                    return
                chaos_delay = ch.frame_delay(ch.local, dst)
        out = []
        i = 0
        n = len(bodies)
        while i < n:
            # greedy envelope up to the byte cap (almost always one pass)
            j = i + 1
            size = len(bodies[i])
            while j < n and size + len(bodies[j]) <= _BATCH_BYTES_CAP:
                size += len(bodies[j])
                j += 1
            if j - i == 1:
                out.append(_LEN.pack(len(bodies[i])))
                out.append(bodies[i])
            else:
                hdr = _array_header(j - i)
                payload_len = len(_BATCH_PREFIX) + len(hdr) + size
                out.append(_LEN.pack(payload_len))
                out.append(_BATCH_PREFIX)
                out.append(hdr)
                out.extend(bodies[i:j])
                WIRE_STATS["batch_frames_sent"] += 1
            WIRE_STATS["frames_sent"] += 1
            i = j
        WIRE_STATS["messages_sent"] += n
        data = b"".join(out)
        if chaos_delay > 0.0:
            # straggler link: emit later, FIFO per connection (a jittered
            # shorter delay never reorders past an earlier longer one)
            ch.count("frames_delayed")
            loop = asyncio.get_running_loop()
            due = max(loop.time() + chaos_delay, self._next_due)
            self._next_due = due
            loop.call_at(due, self._emit, data)
            return
        self._emit(data)

    def _emit(self, data: bytes):
        try:
            self.writer.write(data)
        except Exception:
            pass  # peer gone; readers/futures surface the error


_corks: "weakref.WeakKeyDictionary[asyncio.StreamWriter, _Cork]" = (
    weakref.WeakKeyDictionary()
)


def _cork_for(writer: asyncio.StreamWriter) -> _Cork:
    cork = _corks.get(writer)
    if cork is None:
        cork = _corks[writer] = _Cork(writer)
    return cork


def write_frame(writer: asyncio.StreamWriter, msg: dict) -> None:
    _cork_for(writer).write_body(msgpack.packb(msg, use_bin_type=True))


def write_frame_body(writer: asyncio.StreamWriter, body: bytes) -> None:
    """Queue an already-encoded msgpack map body (template render output)."""
    _cork_for(writer).write_body(body)


class MsgTemplate:
    """Pre-encoded msgpack prefix for messages whose field set repeats.

    Repeated submissions of the same remote function / actor method re-send
    an identical spec modulo the request id and task id: pack the constant
    key/value pairs ONCE and splice only the varying fields per call.  msgpack
    maps are a count header followed by packed k/v pairs in any order, so the
    render is header + constant-bytes + per-var (key-bytes + packb(value))."""

    __slots__ = ("_header", "_const", "_var_keys")

    def __init__(self, const_fields: dict, var_keys: tuple):
        n = len(const_fields) + len(var_keys)
        if n < 16:
            self._header = bytes((0x80 | n,))
        elif n < 1 << 16:
            self._header = b"\xde" + n.to_bytes(2, "big")
        else:
            self._header = b"\xdf" + n.to_bytes(4, "big")
        self._const = b"".join(
            msgpack.packb(k, use_bin_type=True) + msgpack.packb(v, use_bin_type=True)
            for k, v in const_fields.items()
        )
        self._var_keys = tuple(
            msgpack.packb(k, use_bin_type=True) for k in var_keys
        )

    def render(self, *var_values) -> bytes:
        if len(var_values) != len(self._var_keys):
            # a silently-truncated zip would emit a corrupt map (declared
            # pair count > encoded pairs) and poison the whole envelope
            raise ValueError(
                f"template expects {len(self._var_keys)} var values, "
                f"got {len(var_values)}"
            )
        parts = [self._header, self._const]
        for kb, v in zip(self._var_keys, var_values):
            parts.append(kb)
            parts.append(msgpack.packb(v, use_bin_type=True))
        WIRE_STATS["template_renders"] += 1
        return b"".join(parts)


def flush_writer(writer: asyncio.StreamWriter) -> None:
    """Force out corked frames (call before closing a writer)."""
    cork = _corks.get(writer)
    if cork is not None:
        cork.flush()


def fence_close(writer: asyncio.StreamWriter) -> None:
    """Close a peer transport as part of a death-fencing decision.

    With no active network chaos this is flush+close.  While a blackhole
    covers the link the close is DEFERRED until the link heals: a real
    partition delivers no FIN, so the fenced peer must discover its death
    verdict at heal time (refused re-register / FencedError on its next
    authority RPC) instead of being tipped off mid-partition by an EOF that
    could never have reached it."""
    ch = netchaos.NET_CHAOS
    if ch is not None:
        dst = netchaos.link_of(writer)
        if dst is not None and ch.link_down(ch.local, dst):
            ch.count("closes_deferred")

            async def _close_when_healed():
                deadline = asyncio.get_running_loop().time() + 300.0
                while asyncio.get_running_loop().time() < deadline:
                    await asyncio.sleep(0.05)
                    c = netchaos.NET_CHAOS
                    if c is None or not c.link_down(c.local, dst):
                        break
                try:
                    writer.close()
                except Exception:
                    pass

            spawn_bg(_close_when_healed())
            return
    try:
        flush_writer(writer)
        writer.close()
    except Exception:
        pass


def fence_close_conn(conn: "Connection") -> None:
    """Connection.close with fence_close transport semantics (no await:
    fencing paths must not block on a partitioned peer's FIN)."""
    conn._closed = True
    conn._reader_task.cancel()
    fence_close(conn.writer)


class Connection:
    """A client connection with request/response correlation.

    Multiple outstanding requests are multiplexed over one socket; responses
    are matched by request id.  One-way notifications (no reply expected) use
    notify().  Thread-compat: must only be used from the owning event loop.
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._req_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._on_push: Optional[Callable[[dict], Awaitable[None]]] = None
        # authority stamp: fields merged into every outgoing request/notify
        # (worker processes set {"ninc": <node incarnation>, "hep": <head
        # epoch>} after register, so the head can fence RPCs minted under a
        # dead incarnation — and agents can fence calls from a superseded
        # head).  Drivers never stamp; the template fast path is driver-only.
        self.stamp: Optional[dict] = None
        self._reader_task = asyncio.ensure_future(self._read_loop())

    def set_push_handler(self, fn: Callable[[dict], Awaitable[None]]):
        """Handler for unsolicited server->client frames (pubsub pushes)."""
        self._on_push = fn

    async def _read_loop(self):
        try:
            while True:
                frame = await read_frame(self.reader)
                if frame is None:
                    break
                # network-chaos receive filter: frames FROM a partitioned
                # peer are dropped too, so a chaos-enabled process gets a
                # symmetric partition even against peers without a spec
                ch = netchaos.NET_CHAOS
                if ch is not None:
                    peer = netchaos.link_of(self.writer)
                    if peer is not None and ch.link_down(peer, ch.local):
                        ch.count("recv_dropped")
                        continue
                # batch envelopes carry many logical replies/pushes in one
                # physical frame; expand and dispatch each in arrival order
                for msg in iter_messages(frame):
                    rid = msg.get("i")
                    fut = self._pending.pop(rid, None) if rid is not None else None
                    if fut is not None:
                        if callable(fut):  # call_cb fast path: plain callback
                            try:
                                fut(msg)
                            except Exception:
                                # a raising reply callback must not tear down
                                # the connection (and fail every other
                                # pending call)
                                import traceback

                                traceback.print_exc()
                        elif not fut.done():
                            fut.set_result(msg)
                    elif self._on_push is not None:
                        await self._on_push(msg)
        except asyncio.CancelledError:
            raise  # close() cancels the read loop; the finally still settles
        except Exception:
            pass
        finally:
            self._closed = True
            err = ConnectionError("connection closed")
            for fut in self._pending.values():
                if callable(fut):
                    try:
                        fut(None)  # None = connection closed
                    except Exception:
                        pass
                elif not fut.done():
                    fut.set_exception(err)
            self._pending.clear()

    async def call(self, _method: str, timeout: Optional[float] = None, **fields) -> dict:
        chaos = rpc_chaos()
        chaos.maybe_fail(_method)
        if self._closed:
            raise ConnectionError("connection closed")
        d = chaos.delay_s(_method)
        if d:
            await asyncio.sleep(d)  # injected straggler-RPC latency
        rid = next(self._req_ids)
        msg = {"m": _method, "i": rid, **fields}
        if self.stamp:
            msg.update(self.stamp)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        write_frame(self.writer, msg)
        # wait_for wraps the future in a Task + timer handle; skip it on the
        # (hot) untimed path
        reply = await fut if timeout is None else await asyncio.wait_for(fut, timeout)
        if not reply.get("ok", True):
            import pickle

            raise pickle.loads(reply["err"])
        return reply

    def call_cb(self, _method: str, _cb, **fields) -> None:
        """Fire a request and invoke `_cb(reply_msg)` from the read loop when
        the response arrives (`_cb(None)` if the connection dies first).

        The allocation-lean RPC path: no Future, no awaiting coroutine, no
        Task — used by the driver's hot task/actor submission loop where a
        per-call Task measurably caps throughput."""
        chaos = rpc_chaos()
        chaos.maybe_fail(_method)
        if self._closed:
            raise ConnectionError("connection closed")
        rid = next(self._req_ids)
        self._pending[rid] = _cb
        msg = {"m": _method, "i": rid, **fields}
        if self.stamp:
            msg.update(self.stamp)
        d = chaos.delay_s(_method)
        if d:
            asyncio.get_running_loop().call_later(
                d, write_frame, self.writer, msg
            )
            return
        write_frame(self.writer, msg)

    def call_template(self, _method: str, _template: MsgTemplate, _cb, *var_values) -> None:
        """call_cb over a pre-encoded MsgTemplate: the constant part of the
        spec (method, function descriptor, options) was packed once at cache
        time; only the request id and the template's declared var fields are
        encoded per call.  The request id is always the template's FIRST var
        key ("i")."""
        rpc_chaos().maybe_fail(_method)
        if self._closed:
            raise ConnectionError("connection closed")
        rid = next(self._req_ids)
        self._pending[rid] = _cb
        _cork_for(self.writer).write_body(_template.render(rid, *var_values))

    def notify(self, _method: str, **fields) -> None:
        chaos = rpc_chaos()
        chaos.maybe_fail(_method)
        if self._closed:
            raise ConnectionError("connection closed")
        msg = {"m": _method, **fields}
        if self.stamp:
            msg.update(self.stamp)
        d = chaos.delay_s(_method)
        if d:
            asyncio.get_running_loop().call_later(
                d, write_frame, self.writer, msg
            )
            return
        write_frame(self.writer, msg)

    async def close(self):
        self._closed = True
        self._reader_task.cancel()
        try:
            flush_writer(self.writer)  # corked frames out before the FIN
            self.writer.close()
            await self.writer.wait_closed()
        except asyncio.CancelledError:
            raise  # the transport close already went out; don't stall shutdown
        except Exception:
            pass

    @property
    def closed(self) -> bool:
        return self._closed


def addr_list(spec) -> list:
    """Split a comma-separated address list (CA_HEAD_ADDR / CA_HEAD_SOCK may
    name the active head plus its warm standbys)."""
    return [a.strip() for a in (spec or "").split(",") if a.strip()]


class AddrRing:
    """Head-address rotation for HA failover: dialers walk the ring on
    connect failure (active head first, then each standby) and merge the
    `standbys` list every register reply carries, so a client started with
    one address still learns every promotion candidate."""

    def __init__(self, addrs):
        self._addrs: list = []
        self._i = 0
        self.merge(addrs)

    def merge(self, addrs) -> int:
        """Append unseen addresses (order preserved); returns # added."""
        added = 0
        for a in addrs or ():
            if a and a not in self._addrs:
                self._addrs.append(a)
                added += 1
        return added

    @property
    def addrs(self) -> list:
        return list(self._addrs)

    @property
    def current(self):
        return self._addrs[self._i % len(self._addrs)] if self._addrs else None

    def rotate(self):
        """Advance to the next candidate (after a dial/register failure)."""
        if self._addrs:
            self._i = (self._i + 1) % len(self._addrs)
        return self.current

    def promote(self, addr: str) -> None:
        """Make `addr` the ring's current pick (a successful connect)."""
        if addr not in self._addrs:
            self._addrs.append(addr)
        self._i = self._addrs.index(addr)

    def __len__(self) -> int:
        return len(self._addrs)


def parse_addr(addr: str):
    """Split a scheme-prefixed address into ("unix", path) or ("tcp", host, port)."""
    if addr.startswith("unix:"):
        return ("unix", addr[5:])
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        return ("tcp", host, int(port))
    return ("unix", addr)  # bare path


async def connect_addr(addr: str) -> Connection:
    """Dial a scheme-prefixed address (TCP_NODELAY on tcp: small RPC frames
    must not sit in Nagle buffers).

    RAW primitive, deliberately unbounded: production call sites route
    through util.aio.dial(), which wraps this in asyncio.wait_for with
    config.dial_timeout_s and counts/warns on timeouts."""
    parsed = parse_addr(addr)
    if parsed[0] == "unix":
        # ca-lint: ignore[async-unbounded-io] — raw dial primitive (see docstring)
        reader, writer = await asyncio.open_unix_connection(parsed[1])
    else:
        # ca-lint: ignore[async-unbounded-io] — raw dial primitive (see docstring)
        reader, writer = await asyncio.open_connection(parsed[1], parsed[2])
        try:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except BaseException:
            # the socket dialed but configuring it failed (or the dial's
            # wait_for deadline cancelled us right here): don't leak the
            # transport
            writer.close()
            raise
    return Connection(reader, writer)


class BlockingClient:
    """Minimal synchronous client over the same frame protocol — for probe
    tools (head-saturation microbenchmark) that want N independent OS
    threads hammering the head without N event loops.  Sequential
    request/response only; interleaved push frames are skipped."""

    def __init__(self, addr: str):
        parsed = parse_addr(addr)
        if parsed[0] == "unix":
            self._sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            self._sock.connect(parsed[1])
        else:
            self._sock = _socket.create_connection((parsed[1], parsed[2]))
            self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._seq = itertools.count(1)
        self._buf = b""
        self._pending_msgs: list = []  # logical messages from a batch frame

    def _read_frame(self) -> dict:
        if self._pending_msgs:
            return self._pending_msgs.pop(0)
        while True:
            while len(self._buf) < 4:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise ConnectionError("connection closed")
                self._buf += chunk
            (length,) = _LEN.unpack(self._buf[:4])
            while len(self._buf) < 4 + length:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise ConnectionError("connection closed")
                self._buf += chunk
            frame = msgpack.unpackb(self._buf[4 : 4 + length], raw=False)
            self._buf = self._buf[4 + length :]
            if frame.get("m") == "batch":
                # server cork batched our reply with other traffic
                self._pending_msgs = list(frame.get("b") or ())
                if not self._pending_msgs:
                    continue
                return self._pending_msgs.pop(0)
            return frame

    def call(self, method: str, **fields) -> dict:
        rid = next(self._seq)
        fields["m"] = method
        fields["i"] = rid
        payload = msgpack.packb(fields, use_bin_type=True)
        self._sock.sendall(_LEN.pack(len(payload)) + payload)
        while True:
            msg = self._read_frame()
            if msg.get("i") != rid:
                continue  # push/pubsub frame interleaved: not our reply
            if not msg.get("ok", True) and "err" in msg:
                import pickle

                raise pickle.loads(msg["err"])
            return msg

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class Server:
    """Asyncio socket server dispatching frames to a handler; listens on one
    or more addresses (unix and/or tcp) with a shared handler.

    handler(conn_state, msg, reply) — `reply(**fields)` sends the response for
    request-style frames; notifications have no "i" and get no reply.
    """

    def __init__(self, path, handler, on_disconnect=None, fast_handler=None):
        # `path` may be a single address or a list; bare paths mean unix
        self.addrs = [path] if isinstance(path, str) else list(path)
        self.handler = handler
        self.on_disconnect = on_disconnect
        # fast_handler(state, msg, writer) -> bool: synchronous pre-dispatch
        # hook run directly in the read loop; returning True consumes the
        # frame without creating a per-frame asyncio Task (hot-path RPCs)
        self.fast_handler = fast_handler
        self._servers: list = []
        self.bound_addrs: list = []  # resolved (tcp port 0 -> real port)

    async def start(self):
        for addr in self.addrs:
            parsed = parse_addr(addr)
            if parsed[0] == "unix":
                srv = await asyncio.start_unix_server(self._on_client, path=parsed[1])
                self.bound_addrs.append(f"unix:{parsed[1]}")
            else:
                srv = await asyncio.start_server(self._on_client, parsed[1], parsed[2])
                host, port = srv.sockets[0].getsockname()[:2]
                self.bound_addrs.append(f"tcp:{host}:{port}")
            self._servers.append(srv)

    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        sock = writer.get_extra_info("socket")
        if sock is not None and sock.family in (_socket.AF_INET, _socket.AF_INET6):
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        state: Dict[str, Any] = {"writer": writer}
        fast = self.fast_handler
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                # network-chaos receive filter (server side): once this
                # connection's peer is identified (the head labels it at
                # register), frames from a partitioned peer are dropped
                ch = netchaos.NET_CHAOS
                if ch is not None:
                    peer = netchaos.link_of(writer)
                    if peer is not None and ch.link_down(peer, ch.local):
                        ch.count("recv_dropped")
                        continue
                # A batch envelope fans out in-process: every logical message
                # inside it is dispatched exactly as if it had arrived as its
                # own frame, in envelope order.
                for msg in iter_messages(frame):
                    if fast is not None and fast(state, msg, writer):
                        continue
                    # Dispatch each message as its own task so a slow handler
                    # (e.g. actor creation, task execution) doesn't
                    # head-of-line block other requests multiplexed on this
                    # connection.  Tasks start in arrival order (FIFO loop
                    # scheduling), which preserves per-caller actor-call
                    # ordering up to the executor queue.
                    spawn_bg(self._dispatch(state, msg, writer))
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            if self.on_disconnect is not None:
                # masking-safe: a cancelled server task must still run the
                # disconnect bookkeeping AND close the transport below
                # (lazy import: util/__init__ reaches back into core)
                from ..util.aio import finally_await

                await finally_await(self.on_disconnect(state), "on-disconnect")
            try:
                flush_writer(writer)
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, state, msg, writer):
        rid = msg.get("i")

        def reply(**fields):
            if rid is not None:
                write_frame(writer, {"i": rid, "ok": True, **fields})

        def reply_err(exc: BaseException):
            if rid is not None:
                import pickle

                write_frame(writer, {"i": rid, "ok": False, "err": pickle.dumps(exc)})

        try:
            await self.handler(state, msg, reply, reply_err)
        except asyncio.CancelledError:
            raise  # loop shutdown: don't convert cancellation into a reply
        except Exception as e:  # handler bug: report to client
            reply_err(e)

    async def stop(self):
        for srv in self._servers:
            srv.close()
            await srv.wait_closed()
        self._servers = []
