"""Workflow execution engine (analogue of the reference's
python/ray/workflow/workflow_executor.py + api.py).

Steps execute as remote tasks in topological order; each completed step's
result is checkpointed before dependents run, so a crashed workflow resumes
from its last completed frontier. Step keys come from the pickled DAG's node
ids — stable across resume because the DAG itself is checkpointed on first
run and reloaded thereafter.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Any, Dict, List, Optional

from ..core import api as ca
from ..dag.node import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from .storage import WorkflowStorage


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"
    RESUMABLE = "RESUMABLE"


class WorkflowError(RuntimeError):
    pass


def _step_key(node: DAGNode) -> str:
    return f"step_{node._id}_{node._label().replace('/', '_').replace(':', '_')}"


def _check_dag(dag: DAGNode):
    for node in dag._walk():
        if isinstance(node, ClassMethodNode):
            raise WorkflowError(
                "workflows only support task nodes (fn.bind(...)): actor-method "
                "steps are not durable across restarts"
            )


def _execute(
    storage: WorkflowStorage,
    dag: DAGNode,
    input_args: tuple,
    input_kwargs: Dict[str, Any],
    max_step_retries: int,
) -> Any:
    values: Dict[int, Any] = {}
    for node in dag._walk():
        status = storage.load_status()
        if status["status"] == WorkflowStatus.CANCELED:
            raise WorkflowError(f"workflow {storage.workflow_id} canceled")
        key = _step_key(node)
        if isinstance(node, InputNode):
            values[node._id] = node._execute_impl((), {}, input_args, input_kwargs)
            continue
        if isinstance(node, InputAttributeNode):
            args = [values[u._id] for u in node._upstream()]
            values[node._id] = node._execute_impl(args, {}, input_args, input_kwargs)
            continue
        if storage.has_step(key):
            values[node._id] = storage.load_step(key)
            continue
        args = [
            values[a._id] if isinstance(a, DAGNode) else a for a in node._bound_args
        ]
        kwargs = {
            k: values[v._id] if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()
        }
        if isinstance(node, MultiOutputNode):
            value = list(args)
        else:
            assert isinstance(node, FunctionNode)
            attempts = max_step_retries + 1
            last: Optional[BaseException] = None
            for _ in range(attempts):
                try:
                    value = ca.get(node._remote_fn.remote(*args, **kwargs))
                    last = None
                    break
                except Exception as e:  # step failed; retry
                    last = e
            if last is not None:
                raise last
        storage.save_step(key, value)  # checkpoint BEFORE dependents run
        values[node._id] = value
    return values[dag._id]


def _run_to_completion(
    storage: WorkflowStorage,
    dag: DAGNode,
    input_args: tuple,
    input_kwargs: Dict[str, Any],
    max_step_retries: int,
) -> Any:
    import os as _os

    storage.save_status(
        WorkflowStatus.RUNNING,
        started_at=time.time(),
        driver_pid=_os.getpid(),
        error=None,  # clear any stale failure from a previous attempt
    )
    try:
        result = _execute(storage, dag, input_args, input_kwargs, max_step_retries)
    except BaseException as e:
        final = (
            WorkflowStatus.CANCELED
            if storage.load_status()["status"] == WorkflowStatus.CANCELED
            else WorkflowStatus.FAILED
        )
        if final == WorkflowStatus.FAILED:
            storage.save_status(WorkflowStatus.FAILED, error=repr(e))
        raise
    storage.save_step("__output__", result)
    storage.save_status(WorkflowStatus.SUCCEEDED, finished_at=time.time())
    return result


def run(
    dag: DAGNode,
    *input_args,
    workflow_id: Optional[str] = None,
    storage_root: Optional[str] = None,
    max_step_retries: int = 3,
    **input_kwargs,
) -> Any:
    """Run a DAG durably; if `workflow_id` already exists, resume it (a
    SUCCEEDED workflow returns its stored output without re-running)."""
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000)}"
    storage = WorkflowStorage(workflow_id, storage_root)
    if storage.exists():
        return resume(
            workflow_id, storage_root=storage_root, max_step_retries=max_step_retries
        )
    _check_dag(dag)
    storage.create()
    storage.save_dag((dag, input_args, input_kwargs))
    return _run_to_completion(storage, dag, input_args, input_kwargs, max_step_retries)


def run_async(
    dag: DAGNode,
    *input_args,
    workflow_id: Optional[str] = None,
    storage_root: Optional[str] = None,
    max_step_retries: int = 3,
    **input_kwargs,
) -> concurrent.futures.Future:
    ex = concurrent.futures.ThreadPoolExecutor(1)
    fut = ex.submit(
        run,
        dag,
        *input_args,
        workflow_id=workflow_id,
        storage_root=storage_root,
        max_step_retries=max_step_retries,
        **input_kwargs,
    )
    ex.shutdown(wait=False)
    return fut


def resume(
    workflow_id: str,
    *,
    storage_root: Optional[str] = None,
    max_step_retries: int = 3,
) -> Any:
    storage = WorkflowStorage(workflow_id, storage_root)
    if not storage.exists():
        raise WorkflowError(f"no workflow {workflow_id!r}")
    status = storage.load_status()
    if status["status"] == WorkflowStatus.SUCCEEDED:
        return storage.load_step("__output__")
    if status["status"] == WorkflowStatus.CANCELED:
        raise WorkflowError(f"workflow {workflow_id!r} was canceled")
    dag, input_args, input_kwargs = storage.load_dag()
    return _run_to_completion(storage, dag, input_args, input_kwargs, max_step_retries)


def get_status(workflow_id: str, *, storage_root: Optional[str] = None) -> str:
    import os as _os

    storage = WorkflowStorage(workflow_id, storage_root)
    if not storage.exists():
        raise WorkflowError(f"no workflow {workflow_id!r}")
    doc = storage.load_status()
    s = doc["status"]
    if s == WorkflowStatus.RUNNING:
        # a RUNNING workflow whose driver died is resumable, not running
        pid = doc.get("driver_pid")
        alive = False
        if pid:
            try:
                _os.kill(pid, 0)
                alive = True
            except PermissionError:
                alive = True
            except (ProcessLookupError, OSError):
                alive = False
        if not alive:
            return WorkflowStatus.RESUMABLE
    return s


def get_output(workflow_id: str, *, storage_root: Optional[str] = None) -> Any:
    storage = WorkflowStorage(workflow_id, storage_root)
    if not storage.exists():
        raise WorkflowError(f"no workflow {workflow_id!r}")
    if storage.load_status()["status"] != WorkflowStatus.SUCCEEDED:
        raise WorkflowError(f"workflow {workflow_id!r} has no output yet")
    return storage.load_step("__output__")


def get_metadata(workflow_id: str, *, storage_root: Optional[str] = None) -> Dict[str, Any]:
    storage = WorkflowStorage(workflow_id, storage_root)
    if not storage.exists():
        raise WorkflowError(f"no workflow {workflow_id!r}")
    meta = storage.load_status()
    meta["completed_steps"] = sorted(
        k for k in storage.completed_steps() if k != "__output__"
    )
    return meta


def list_all(*, storage_root: Optional[str] = None) -> List[tuple]:
    out = []
    for wid in WorkflowStorage.list_workflows(storage_root):
        try:
            out.append((wid, WorkflowStorage(wid, storage_root).load_status()["status"]))
        except Exception:
            continue
    return out


def cancel(workflow_id: str, *, storage_root: Optional[str] = None):
    storage = WorkflowStorage(workflow_id, storage_root)
    if not storage.exists():
        raise WorkflowError(f"no workflow {workflow_id!r}")
    storage.save_status(WorkflowStatus.CANCELED)


def delete(workflow_id: str, *, storage_root: Optional[str] = None):
    WorkflowStorage(workflow_id, storage_root).delete()


# --------------------------------------------------------------------- events
class EventListener:
    """Blocks a workflow step until an external event arrives (reference
    workflow event system: workflow/api.py wait_for_event + event_listener).
    Subclass and implement poll_for_event(); the returned payload becomes
    the step's checkpointed result, so a resumed workflow never re-waits
    for an event it already received."""

    def poll_for_event(self) -> Any:
        raise NotImplementedError


class KVEventListener(EventListener):
    """Built-in listener over the cluster KV: completes when some process
    calls ``workflow.signal_event(key, payload)``."""

    NS = "__workflow_events__"

    def __init__(self, key: str, poll_interval_s: float = 0.1,
                 timeout_s: Optional[float] = None):
        self.key = key
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s

    def poll_for_event(self) -> Any:
        import pickle as _pickle

        from ..core.worker import global_worker

        w = global_worker()
        deadline = (
            time.monotonic() + self.timeout_s if self.timeout_s is not None else None
        )
        while True:
            v = w.head_call("kv_get", ns=self.NS, key=self.key)["value"]
            if v is not None:
                return _pickle.loads(v)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"event {self.key!r} did not arrive")
            time.sleep(self.poll_interval_s)


def signal_event(key: str, payload: Any = None):
    """Deliver the event that a KVEventListener step is waiting for."""
    import pickle as _pickle

    from ..core.worker import global_worker

    global_worker().head_call(
        "kv_put", ns=KVEventListener.NS, key=key, value=_pickle.dumps(payload)
    )


def wait_for_event(listener_cls, *args, **kwargs) -> DAGNode:
    """A workflow step that completes when `listener_cls(*args).poll_for_event()`
    returns; use its node as an upstream dependency of steps that need the
    event payload."""
    if not (isinstance(listener_cls, type) and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event expects an EventListener subclass")

    @ca.remote
    def __wf_wait_for_event(cls, cls_args, cls_kwargs):
        return cls(*cls_args, **cls_kwargs).poll_for_event()

    return __wf_wait_for_event.bind(listener_cls, args, kwargs)
