"""Workflow storage: per-workflow checkpoint directory (analogue of the
reference's python/ray/workflow/workflow_storage.py).

Layout under <storage_root>/<workflow_id>/:
    status.json           — RUNNING | SUCCEEDED | FAILED | CANCELED + metadata
    dag.pkl               — the cloudpickled DAG (for resume)
    steps/<step_key>.pkl  — checkpointed result of each completed step
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle


def default_storage_root() -> str:
    return os.environ.get(
        "CA_WORKFLOW_STORAGE", os.path.expanduser("~/ca_workflows")
    )


class WorkflowStorage:
    def __init__(self, workflow_id: str, storage_root: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = os.path.join(storage_root or default_storage_root(), workflow_id)
        self.steps_dir = os.path.join(self.root, "steps")

    def create(self):
        os.makedirs(self.steps_dir, exist_ok=True)

    def exists(self) -> bool:
        return os.path.exists(os.path.join(self.root, "status.json"))

    # ------------------------------------------------------------ status
    def save_status(self, status: str, **extra):
        self.create()
        path = os.path.join(self.root, "status.json")
        doc = {"status": status, "updated_at": time.time(), **extra}
        if os.path.exists(path):
            with open(path) as f:
                old = json.load(f)
            doc = {**old, **doc}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def load_status(self) -> Dict[str, Any]:
        with open(os.path.join(self.root, "status.json")) as f:
            return json.load(f)

    # --------------------------------------------------------------- dag
    def save_dag(self, dag):
        self.create()
        with open(os.path.join(self.root, "dag.pkl"), "wb") as f:
            cloudpickle.dump(dag, f)

    def load_dag(self):
        with open(os.path.join(self.root, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    # ------------------------------------------------------------- steps
    def _step_path(self, step_key: str) -> str:
        return os.path.join(self.steps_dir, f"{step_key}.pkl")

    def has_step(self, step_key: str) -> bool:
        return os.path.exists(self._step_path(step_key))

    def save_step(self, step_key: str, value: Any):
        tmp = self._step_path(step_key) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._step_path(step_key))

    def load_step(self, step_key: str) -> Any:
        with open(self._step_path(step_key), "rb") as f:
            return cloudpickle.load(f)

    def completed_steps(self) -> List[str]:
        if not os.path.isdir(self.steps_dir):
            return []
        return [f[:-4] for f in os.listdir(self.steps_dir) if f.endswith(".pkl")]

    def delete(self):
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)

    @staticmethod
    def list_workflows(storage_root: Optional[str] = None) -> List[str]:
        root = storage_root or default_storage_root()
        if not os.path.isdir(root):
            return []
        return [
            d
            for d in sorted(os.listdir(root))
            if os.path.exists(os.path.join(root, d, "status.json"))
        ]
