"""cluster_anywhere_tpu.workflow: durable execution of task DAGs (analogue of
the reference's Ray Workflow, python/ray/workflow/ — WorkflowExecutor,
workflow_storage.py checkpointing, recovery from storage).

    @ca.remote
    def fetch(x): ...
    @ca.remote
    def combine(a, b): ...

    dag = combine.bind(fetch.bind(1), fetch.bind(2))
    result = workflow.run(dag, workflow_id="my_wf")

Every step's result is checkpointed; `workflow.resume("my_wf")` after a crash
re-runs only the steps that never completed.
"""

from .api import (
    EventListener,
    KVEventListener,
    WorkflowStatus,
    cancel,
    delete,
    get_metadata,
    get_output,
    get_status,
    list_all,
    resume,
    signal_event,
    wait_for_event,
    run,
    run_async,
)

__all__ = [
    "run",
    "run_async",
    "resume",
    "get_status",
    "get_output",
    "get_metadata",
    "list_all",
    "cancel",
    "delete",
    "WorkflowStatus",
]
