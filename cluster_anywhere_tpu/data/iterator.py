"""DataIterator: batched iteration with prefetch and local shuffle (analogue
of python/ray/data/iterator.py DataIterator / iter_batches).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ..core import api as ca
from .block import Block, BlockAccessor


class DataIterator:
    def __init__(self, dataset):
        self._dataset = dataset

    def _block_iter(self, prefetch_blocks: int = 2) -> Iterator[Block]:
        """Pull blocks with a small prefetch window (refs are fetched ahead
        while the consumer processes the current block)."""
        bundles = self._dataset._execute()
        window: deque = deque()
        for bundle in bundles:
            window.append(bundle.ref)
            if len(window) > prefetch_blocks:
                yield ca.get(window.popleft())
        while window:
            yield ca.get(window.popleft())

    def iter_rows(self) -> Iterator[Any]:
        for block in self._block_iter():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: Optional[str] = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_batches: int = 1,
        **_ignored,
    ) -> Iterator[Any]:
        if local_shuffle_buffer_size:
            yield from self._iter_shuffled(
                batch_size or 256,
                batch_format,
                drop_last,
                local_shuffle_buffer_size,
                local_shuffle_seed,
            )
            return
        carry: Optional[Block] = None
        for block in self._block_iter(prefetch_blocks=max(1, prefetch_batches)):
            if carry is not None:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if batch_size is None:
                if n:
                    yield acc.to_batch(batch_format)
                continue
            start = 0
            while n - start >= batch_size:
                yield BlockAccessor.for_block(
                    acc.slice(start, start + batch_size)
                ).to_batch(batch_format)
                start += batch_size
            if start < n:
                carry = acc.slice(start, n)
        if carry is not None:
            acc = BlockAccessor.for_block(carry)
            if acc.num_rows() and not drop_last:
                yield acc.to_batch(batch_format)

    def _iter_shuffled(self, batch_size, batch_format, drop_last, buffer_size, seed):
        rng = np.random.default_rng(seed)
        buf: Optional[Block] = None
        for block in self._block_iter():
            buf = block if buf is None else BlockAccessor.concat([buf, block])
            acc = BlockAccessor.for_block(buf)
            while acc.num_rows() >= max(buffer_size, batch_size):
                idx = rng.permutation(acc.num_rows())
                take, rest = idx[:batch_size], idx[batch_size:]
                # keep the permuted order within the batch (sorting would undo
                # the shuffle for time-ordered data); the remainder buffer can
                # stay sorted for cheaper slicing
                yield BlockAccessor.for_block(acc.take_indices(take)).to_batch(
                    batch_format
                )
                buf = acc.take_indices(np.sort(rest))
                acc = BlockAccessor.for_block(buf)
        if buf is not None:
            acc = BlockAccessor.for_block(buf)
            idx = rng.permutation(acc.num_rows())
            start = 0
            while start < len(idx):
                chunk = idx[start : start + batch_size]
                if len(chunk) < batch_size and drop_last:
                    break
                yield BlockAccessor.for_block(acc.take_indices(chunk)).to_batch(
                    batch_format
                )
                start += batch_size

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = 256, dtypes=None, device=None, **kw
    ) -> Iterator[Dict[str, Any]]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", **kw):
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = v
                    continue
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes is not None:
                    t = t.to(dtypes[k] if isinstance(dtypes, dict) else dtypes)
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        dtypes=None,
        sharding=None,
        prefetch: int = 1,
        **kw,
    ) -> Iterator[Dict[str, Any]]:
        """numpy batches materialized as jax.Arrays on device (the TPU-native
        counterpart of iter_torch_batches).

        ``sharding``: optional jax.sharding.Sharding (e.g. a NamedSharding
        over the dp axis) applied at device_put, so each batch lands already
        distributed.  ``prefetch`` batches are device_put ahead of the one
        being consumed — jax transfers are async, so the next host->device
        copy overlaps the caller's compute on the current batch.
        """
        import collections

        import jax

        def to_device(batch):
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = v
                    continue
                arr = np.ascontiguousarray(v)
                if dtypes is not None:
                    arr = arr.astype(dtypes[k] if isinstance(dtypes, dict) else dtypes)
                out[k] = jax.device_put(arr, sharding)
            return out

        window: collections.deque = collections.deque()
        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", **kw):
            window.append(to_device(batch))
            if len(window) > max(prefetch, 0):
                yield window.popleft()
        while window:
            yield window.popleft()

    def materialize(self):
        return self._dataset.materialize()

    def stats(self) -> str:
        return self._dataset.stats()
