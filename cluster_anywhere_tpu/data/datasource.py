"""Datasources: pluggable readers producing ReadTasks, and file writers
(analogue of the reference's python/ray/data/datasource/ — Datasource,
ReadTask, and the file-based implementations in
python/ray/data/_internal/datasource/).

A ``ReadTask`` is a zero-arg callable returning an iterator of blocks; read
tasks execute remotely inside the streaming executor like any other map task.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .block import Block, build_block

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None


class ReadTask:
    def __init__(self, fn: Callable[[], Iterator[Block]], num_rows: Optional[int] = None):
        self._fn = fn
        self.num_rows = num_rows

    def __call__(self) -> Iterator[Block]:
        return self._fn()


class Datasource:
    """Override get_read_tasks; optionally estimate size."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    @property
    def name(self) -> str:
        return type(self).__name__


# --------------------------------------------------------------------- range


class RangeDatasource(Datasource):
    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n, shape = self.n, self.tensor_shape
        parallelism = max(1, min(parallelism, n) if n else 1)
        per = -(-n // parallelism) if n else 0
        tasks = []
        for start in range(0, n, per):
            end = min(start + per, n)

            def read(start=start, end=end) -> Iterator[Block]:
                ids = np.arange(start, end, dtype=np.int64)
                if shape is None:
                    yield build_block({"id": ids})
                else:
                    data = np.broadcast_to(
                        ids.reshape((-1,) + (1,) * len(shape)), (end - start,) + shape
                    ).copy()
                    yield build_block({"data": data})

            tasks.append(ReadTask(read, num_rows=end - start))
        return tasks or [ReadTask(lambda: iter([build_block({"id": np.array([], np.int64)})]), 0)]


class ItemsDatasource(Datasource):
    def __init__(self, items: Sequence[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from .block import ITEM_COL

        items = self.items
        n = len(items)
        parallelism = max(1, min(parallelism, n) if n else 1)
        per = -(-n // parallelism) if n else 0
        tasks = []
        for start in range(0, n, per):
            chunk = items[start : start + per]

            def read(chunk=chunk) -> Iterator[Block]:
                if chunk and all(isinstance(r, dict) for r in chunk):
                    keys = list(chunk[0].keys())
                    if all(list(r.keys()) == keys for r in chunk):
                        yield build_block(
                            {k: np.asarray([r[k] for r in chunk]) for k in keys}
                        )
                        return
                try:
                    yield build_block({ITEM_COL: np.asarray(chunk)})
                except Exception:
                    yield chunk  # heterogeneous rows: simple list block

            tasks.append(ReadTask(read, num_rows=len(chunk)))
        return tasks or [ReadTask(lambda: iter([[]]), 0)]


class BlocksDatasource(Datasource):
    """Pre-materialized blocks (from_numpy/from_pandas/from_arrow)."""

    def __init__(self, blocks: List[Block]):
        self.blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from .block import BlockAccessor

        return [
            ReadTask(lambda b=b: iter([b]), num_rows=BlockAccessor(b).num_rows())
            for b in self.blocks
        ]


# --------------------------------------------------------------------- files


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    f
                    for f in glob.glob(os.path.join(p, "**", "*"), recursive=True)
                    if os.path.isfile(f) and (suffix is None or f.endswith(suffix))
                )
            )
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files for {paths}")
    return out


class FileBasedDatasource(Datasource):
    _suffix: Optional[str] = None

    def __init__(self, paths, **kw):
        self.paths = _expand_paths(paths, self._suffix)
        self.kw = kw

    def _read_file(self, path: str) -> Iterator[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        # one task per group of files, groups sized to hit `parallelism`
        n = len(self.paths)
        parallelism = max(1, min(parallelism, n))
        per = -(-n // parallelism)
        tasks = []
        for start in range(0, n, per):
            group = self.paths[start : start + per]

            def read(group=group) -> Iterator[Block]:
                for path in group:
                    yield from self._read_file(path)

            tasks.append(ReadTask(read))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _suffix = ".parquet"

    def _read_file(self, path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        t = pq.read_table(path, columns=self.kw.get("columns"))
        yield t


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        from pyarrow import csv as pacsv

        yield pacsv.read_csv(path)


class JSONDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        import json

        rows = []
        with open(path) as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                rows = json.load(f)
            else:  # jsonl
                rows = [json.loads(line) for line in f if line.strip()]
        yield pa.Table.from_pylist(rows)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        with open(path) as f:
            lines = [line.rstrip("\n") for line in f]
        if self.kw.get("drop_empty_lines", True):
            lines = [line for line in lines if line]
        yield build_block({"text": np.asarray(lines, dtype=object)})


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        t = pa.table({"bytes": pa.array([data], type=pa.binary())})
        if self.kw.get("include_paths"):
            t = t.append_column("path", pa.array([path]))
        yield t


class NumpyDatasource(FileBasedDatasource):
    _suffix = ".npy"

    def _read_file(self, path: str) -> Iterator[Block]:
        arr = np.load(path, allow_pickle=True)
        yield build_block({"data": arr})


# -------------------------------------------------------------------- writes


def write_block(block: Block, path: str, file_format: str, index: int, **kw) -> str:
    from .block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    acc = BlockAccessor.for_block(block)
    fn = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(acc.to_arrow(), fn, **kw)
    elif file_format == "csv":
        from pyarrow import csv as pacsv

        pacsv.write_csv(acc.to_arrow(), fn)
    elif file_format == "json":
        import json

        with open(fn, "w") as f:
            for row in acc.iter_rows():
                if not isinstance(row, dict):
                    row = {"item": row}
                f.write(json.dumps({k: _json_safe(v) for k, v in row.items()}) + "\n")
    elif file_format == "npy":
        batch = acc.to_numpy_batch()
        col = kw.get("column") or next(iter(batch))
        np.save(fn, batch[col])
    else:
        raise ValueError(f"unknown write format {file_format}")
    return fn


def _json_safe(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v
