"""Transform specs: the serializable description of one map-like operator,
applied to blocks inside remote tasks (analogue of the reference's
python/ray/data/_internal/planner/plan_udf_map_op.py batch/row adapters).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Iterator, List

import numpy as np

from .block import Block, BlockAccessor, ITEM_COL, build_block
from .plan import MapLike


def to_spec(op: MapLike) -> Dict[str, Any]:
    return {
        "kind": op.kind,
        "fn": op.fn,
        "fn_args": op.fn_args,
        "fn_kwargs": op.fn_kwargs,
        "ctor_args": op.fn_constructor_args,
        "ctor_kwargs": op.fn_constructor_kwargs,
        "batch_size": op.batch_size,
        "batch_format": op.batch_format,
        "is_actor": op.is_actor,
    }


def instantiate_callables(chain: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Instantiate class UDFs once per worker (actor-compute path)."""
    out = []
    for spec in chain:
        spec = dict(spec)
        if isinstance(spec["fn"], type):
            spec["fn"] = spec["fn"](*spec["ctor_args"], **spec["ctor_kwargs"])
        out.append(spec)
    return out


def _iter_batches(block: Block, batch_size, batch_format) -> Iterator[Any]:
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    if batch_size is None or batch_size >= n:
        yield acc.to_batch(batch_format)
        return
    for start in range(0, n, batch_size):
        yield BlockAccessor.for_block(acc.slice(start, min(start + batch_size, n))).to_batch(
            batch_format
        )


def _wrap_row(row: Any) -> Any:
    return row if isinstance(row, dict) else {ITEM_COL: row}


def apply_transform(spec: Dict[str, Any], block: Block) -> Iterator[Block]:
    kind = spec["kind"]
    fn = spec["fn"]
    if isinstance(fn, type):  # task-compute class UDF: construct per block
        fn = fn(*spec["ctor_args"], **spec["ctor_kwargs"])
    args, kwargs = spec.get("fn_args", ()), spec.get("fn_kwargs", {})
    acc = BlockAccessor.for_block(block)

    if kind == "map_batches":
        for batch in _iter_batches(block, spec["batch_size"], spec["batch_format"]):
            out = fn(batch, *args, **kwargs)
            if out is None:
                continue
            # generator UDFs yield multiple batches; anything else (dict,
            # DataFrame, Table, ndarray, list of rows) is a single batch
            if inspect.isgenerator(out) or (
                hasattr(out, "__next__") and hasattr(out, "__iter__")
            ):
                for o in out:
                    yield build_block(o)
            else:
                yield build_block(out)
    elif kind == "map":
        rows = [_wrap_row(fn(r, *args, **kwargs)) for r in acc.iter_rows()]
        yield _rows_to_block(rows)
    elif kind == "flat_map":
        rows = []
        for r in acc.iter_rows():
            rows.extend(_wrap_row(o) for o in fn(r, *args, **kwargs))
        yield _rows_to_block(rows)
    elif kind == "filter":
        keep = [i for i, r in enumerate(acc.iter_rows()) if fn(r, *args, **kwargs)]
        yield acc.take_indices(np.asarray(keep, dtype=np.int64))
    elif kind == "add_column":
        name, col_fn = args
        t = acc.to_arrow()
        import pyarrow as pa

        col = col_fn(acc.to_batch("numpy"))
        col = np.asarray(col)
        if col.ndim > 1:
            from .block import _TensorArray

            arr, shape = _TensorArray.to_arrow(col)
            t = t.append_column(name, arr)
            meta = {**(t.schema.metadata or {}), f"tensor:{name}".encode(): repr(list(shape)).encode()}
            t = t.replace_schema_metadata(meta)
        else:
            t = t.append_column(name, pa.array(col))
        yield t
    elif kind == "drop_columns":
        t = acc.to_arrow()
        dropped = [c for c in args[0] if c in t.column_names]
        t = t.drop_columns(dropped)
        yield _remap_tensor_meta(t, {c: None for c in dropped})
    elif kind == "select_columns":
        keep = list(args[0])
        t = acc.to_arrow().select(keep)
        all_names = set(keep)
        yield _remap_tensor_meta(
            t, {}, keep=all_names
        )
    elif kind == "rename_columns":
        mapping = args[0]
        t = acc.to_arrow()
        t = t.rename_columns([mapping.get(c, c) for c in t.column_names])
        yield _remap_tensor_meta(t, mapping)
    else:
        raise ValueError(f"unknown transform kind {kind}")


def _remap_tensor_meta(t, mapping, keep=None):
    """Rewrite 'tensor:<name>' schema-metadata keys through a column rename.

    mapping: old-name -> new-name, or -> None to drop the key (drop_columns).
    keep: if given, only names in this set survive (select_columns).
    Without this, a renamed tensor column loses its shape mapping and decodes
    as flat per-row lists (ADVICE r1)."""
    meta = t.schema.metadata or {}
    if not meta:
        return t
    out = {}
    for k, v in meta.items():
        ks = k.decode() if isinstance(k, bytes) else k
        if ks.startswith("tensor:"):
            name = ks[len("tensor:"):]
            if keep is not None and name not in keep:
                continue
            if name in mapping:
                new = mapping[name]
                if new is None:
                    continue
                out[f"tensor:{new}".encode()] = v
                continue
        out[k] = v
    return t.replace_schema_metadata(out)


def _rows_to_block(rows: List[dict]) -> Block:
    if not rows:
        return []
    keys = list(rows[0].keys())
    if all(isinstance(r, dict) and list(r.keys()) == keys for r in rows):
        try:
            return build_block({k: np.asarray([r[k] for r in rows]) for k in keys})
        except Exception:
            pass
    return rows
