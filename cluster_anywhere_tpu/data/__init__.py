"""Data library: lazy streaming datasets over the distributed object store
(analogue of the reference's python/ray/data/ — Dataset, read APIs,
streaming executor).

    import cluster_anywhere_tpu.data as cad
    ds = cad.range(1000).map_batches(lambda b: {"x": b["id"] * 2})
    for batch in ds.iter_batches(batch_size=128):
        ...
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from .aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Quantile, Std, Sum
from .block import Block, BlockAccessor
from .dataset import Dataset, MaterializedDataset
from .datasource import (
    BinaryDatasource,
    BlocksDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
)
from .iterator import DataIterator
from .plan import LogicalPlan, Read, ReadIterator


def _from_source(source: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(LogicalPlan([Read(source, parallelism)]))


def range(n: int, *, parallelism: int = -1, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return _from_source(RangeDatasource(n), override_num_blocks or parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1, override_num_blocks=None) -> Dataset:
    return _from_source(RangeDatasource(n, tuple(shape)), override_num_blocks or parallelism)


def from_items(items: Sequence[Any], *, parallelism: int = -1, override_num_blocks=None) -> Dataset:
    return _from_source(ItemsDatasource(items), override_num_blocks or parallelism)


def from_torch(torch_dataset, *, override_num_blocks: Optional[int] = None) -> Dataset:
    """Dataset over a torch map-style dataset (reference data/read_api.py
    from_torch): the dataset's values become the Dataset's rows."""
    import builtins

    # builtins.range: this module's own range() is the Dataset factory.
    # Raw values, not {"item": ...} wrappers: ItemsDatasource already speaks
    # from_items row semantics
    n = len(torch_dataset)
    items = [torch_dataset[i] for i in builtins.range(n)]
    return _from_source(
        ItemsDatasource(items), override_num_blocks or -1
    )


def from_huggingface(hf_dataset, *, override_num_blocks: Optional[int] = None) -> Dataset:
    """Dataset over a Hugging Face datasets.Dataset (reference
    from_huggingface): column-dict rows pass through unchanged."""
    items = [dict(r) for r in hf_dataset]
    return _from_source(ItemsDatasource(items), override_num_blocks or -1)


def from_numpy(arr, column: str = "data") -> Dataset:
    import numpy as np

    from .block import build_block

    arrs = arr if isinstance(arr, list) else [arr]
    blocks = [build_block({column: np.asarray(a)}) for a in arrs]
    return _from_source(BlocksDatasource(blocks))


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa

    dfs = dfs if isinstance(dfs, list) else [dfs]
    blocks = [pa.Table.from_pandas(df, preserve_index=False) for df in dfs]
    return _from_source(BlocksDatasource(blocks))


def from_arrow(tables) -> Dataset:
    tables = tables if isinstance(tables, list) else [tables]
    return _from_source(BlocksDatasource(list(tables)))


def read_datasource(source: Datasource, *, parallelism: int = -1) -> Dataset:
    return _from_source(source, parallelism)


def from_generator(gen_fn, *, rows_per_block: int = 256) -> Dataset:
    """Dataset fed lazily by a python generator running as ONE streaming
    remote task (num_returns="streaming"): blocks materialize with
    producer-side backpressure as iter_batches consumes them."""
    return Dataset(LogicalPlan([ReadIterator(gen_fn, rows_per_block)]))


def read_parquet(paths, *, columns: Optional[List[str]] = None, parallelism: int = -1, **kw) -> Dataset:
    return _from_source(ParquetDatasource(paths, columns=columns, **kw), parallelism)


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _from_source(CSVDatasource(paths, **kw), parallelism)


def read_json(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _from_source(JSONDatasource(paths, **kw), parallelism)


def read_text(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _from_source(TextDatasource(paths, **kw), parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1) -> Dataset:
    return _from_source(BinaryDatasource(paths, include_paths=include_paths), parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return _from_source(NumpyDatasource(paths, **kw), parallelism)


__all__ = [
    "Dataset",
    "MaterializedDataset",
    "DataIterator",
    "Datasource",
    "ReadTask",
    "BlockAccessor",
    "Block",
    "AggregateFn",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
    "AbsMax",
    "Quantile",
    "range",
    "range_tensor",
    "from_items",
    "from_torch",
    "from_huggingface",
    "from_generator",
    "from_numpy",
    "from_pandas",
    "from_arrow",
    "read_datasource",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_text",
    "read_binary_files",
    "read_numpy",
]
