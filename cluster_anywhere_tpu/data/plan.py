"""Logical plan: a linear chain of operators over blocks (compact analogue of
the reference's python/ray/data/_internal/logical/ LogicalPlan + optimizer).

Map-like operators that execute with the same compute strategy are *fused*
into a single remote task per block by the executor (the reference does this
in its OperatorFusionRule); all-to-all operators are barriers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .datasource import Datasource


class LogicalOp:
    name: str = "op"


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1

    @property
    def name(self) -> str:
        return f"Read{self.datasource.name}"


@dataclass
class MapLike(LogicalOp):
    """map_batches / map / filter / flat_map / column ops.

    fn is either a plain callable (task compute) or a class (actor compute,
    instantiated `concurrency` times).
    """

    kind: str
    fn: Any
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = field(default_factory=dict)
    batch_size: Optional[int] = None
    batch_format: Optional[str] = "numpy"
    concurrency: Optional[int] = None
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    is_actor: bool = False

    @property
    def name(self) -> str:
        fn_name = getattr(self.fn, "__name__", type(self.fn).__name__)
        return f"{self.kind}({fn_name})"


@dataclass
class AllToAll(LogicalOp):
    kind: str  # repartition | random_shuffle | sort | aggregate
    options: Dict[str, Any] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.kind


@dataclass
class Limit(LogicalOp):
    n: int

    @property
    def name(self) -> str:
        return f"limit({self.n})"


@dataclass
class UnionOp(LogicalOp):
    others: List["LogicalPlan"]
    name = "union"


@dataclass
class ZipOp(LogicalOp):
    other: "LogicalPlan"
    name = "zip"


@dataclass
class InputData(LogicalOp):
    """Already-materialized bundles (output of a previous execution)."""

    bundles: List[Any]
    name = "input"


@dataclass
class ReadIterator(LogicalOp):
    """Blocks produced lazily by ONE remote generator task with streaming
    returns: end-to-end backpressure from iter_batches down to the producing
    python generator (num_returns='streaming')."""

    gen_fn: Any  # picklable generator function yielding rows or dict batches
    rows_per_block: int = 256
    name = "ReadIterator"


class LogicalPlan:
    def __init__(self, ops: List[LogicalOp]):
        self.ops = ops

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def __repr__(self):
        return " -> ".join(op.name for op in self.ops)
