"""GroupedData (analogue of python/ray/data/grouped_data.py)."""

from __future__ import annotations

from typing import Callable, Optional

from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .plan import AllToAll


class GroupedData:
    def __init__(self, dataset, key: Optional[str]):
        self._dataset = dataset
        self._key = key

    def aggregate(self, *aggs: AggregateFn):
        from .dataset import Dataset

        return Dataset(
            self._dataset._plan.with_op(
                AllToAll("aggregate", {"key": self._key, "aggs": list(aggs)})
            )
        )

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof=ddof))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"):
        """Sort by key, then apply fn per group (runs as a map over
        key-partitioned blocks; each group lives wholly in one block)."""
        if self._key is None:
            return self._dataset.map_batches(fn, batch_format=batch_format)
        sorted_ds = self._dataset.sort(self._key)

        key = self._key

        def apply_groups(batch):
            import numpy as np

            from .block import BlockAccessor, build_block

            keys = batch[key]
            outs = []
            if len(keys) == 0:
                return None
            bounds = [0] + [
                i for i in range(1, len(keys)) if keys[i] != keys[i - 1]
            ] + [len(keys)]
            for lo, hi in zip(bounds[:-1], bounds[1:]):
                group = {k: v[lo:hi] for k, v in batch.items()}
                out = fn(group)
                if out is not None:
                    outs.append(build_block(out))
            if not outs:
                return None
            return BlockAccessor.for_block(BlockAccessor.concat(outs)).to_numpy_batch()

        return sorted_ds.map_batches(apply_groups, batch_format="numpy")
