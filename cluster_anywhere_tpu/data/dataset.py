"""Dataset: lazy, distributed collection of blocks (analogue of the
reference's python/ray/data/dataset.py Dataset, 86.9k LoC surface compressed
to the operations that carry its semantics).

All transforms are lazy — they append to the logical plan; execution happens
on consumption (iterate/take/write/materialize) through the streaming
executor with backpressure (python/ray/data/_internal/execution/streaming_executor.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

from ..core import api as ca
from .block import Block, BlockAccessor, ITEM_COL
from .executor import ExecStats, RefBundle, StreamingExecutor
from .plan import (
    AllToAll,
    InputData,
    Limit,
    LogicalPlan,
    MapLike,
    Read,
    UnionOp,
    ZipOp,
)


class Dataset:
    def __init__(self, plan: LogicalPlan):
        self._plan = plan
        self._stats = ExecStats()

    # ------------------------------------------------------------ transforms
    def _map_op(self, kind: str, fn, **kw) -> "Dataset":
        is_actor = isinstance(fn, type)
        concurrency = kw.pop("concurrency", None)
        if isinstance(concurrency, tuple):
            concurrency = concurrency[1]
        op = MapLike(
            kind=kind,
            fn=fn,
            fn_args=kw.pop("fn_args", ()),
            fn_kwargs=kw.pop("fn_kwargs", {}),
            fn_constructor_args=kw.pop("fn_constructor_args", ()),
            fn_constructor_kwargs=kw.pop("fn_constructor_kwargs", {}),
            batch_size=kw.pop("batch_size", None),
            batch_format=kw.pop("batch_format", "numpy"),
            concurrency=concurrency,
            num_cpus=kw.pop("num_cpus", None),
            num_tpus=kw.pop("num_tpus", None),
            is_actor=is_actor,
        )
        if kw:
            raise TypeError(f"unknown arguments: {sorted(kw)}")
        return Dataset(self._plan.with_op(op))

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: Optional[str] = "numpy",
        compute=None,
        concurrency=None,
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        **_ignored,
    ) -> "Dataset":
        return self._map_op(
            "map_batches",
            fn,
            batch_size=batch_size,
            batch_format=batch_format,
            concurrency=concurrency,
            fn_args=fn_args,
            fn_kwargs=fn_kwargs or {},
            fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs or {},
            num_cpus=num_cpus,
            num_tpus=num_tpus,
        )

    def map(self, fn, *, concurrency=None, num_cpus=None, **_ignored) -> "Dataset":
        return self._map_op("map", fn, concurrency=concurrency, num_cpus=num_cpus)

    def flat_map(self, fn, *, concurrency=None, **_ignored) -> "Dataset":
        return self._map_op("flat_map", fn, concurrency=concurrency)

    def filter(self, fn, *, concurrency=None, **_ignored) -> "Dataset":
        return self._map_op("filter", fn, concurrency=concurrency)

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self._map_op("add_column", _named("add_column", fn), fn_args=(name, fn))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._map_op("drop_columns", _named("drop_columns"), fn_args=(cols,))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._map_op("select_columns", _named("select_columns"), fn_args=(cols,))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._map_op("rename_columns", _named("rename_columns"), fn_args=(mapping,))

    def repartition(self, num_blocks: int, **_ignored) -> "Dataset":
        return Dataset(
            self._plan.with_op(AllToAll("repartition", {"num_blocks": num_blocks}))
        )

    def random_shuffle(self, *, seed: Optional[int] = None, **_ignored) -> "Dataset":
        return Dataset(self._plan.with_op(AllToAll("random_shuffle", {"seed": seed})))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(
            self._plan.with_op(AllToAll("randomize_block_order", {"seed": seed}))
        )

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(
            self._plan.with_op(AllToAll("sort", {"key": key, "descending": descending}))
        )

    def groupby(self, key: Optional[str]) -> "GroupedData":
        from .grouped_data import GroupedData

        return GroupedData(self, key)

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(Limit(n)))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(UnionOp([o._plan for o in others])))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(ZipOp(other._plan)))

    # ----------------------------------------------------------- consumption
    def _execute(self) -> Iterator[RefBundle]:
        self._stats = ExecStats()
        return StreamingExecutor(self._plan, self._stats).execute()

    def iter_internal_ref_bundles(self) -> Iterator[RefBundle]:
        return self._execute()

    def materialize(self) -> "MaterializedDataset":
        bundles = list(self._execute())
        return MaterializedDataset(bundles, self._stats)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for bundle in self.limit(limit)._execute():
            block = ca.get(bundle.ref)
            out.extend(BlockAccessor.for_block(block).iter_rows())
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self, limit: Optional[int] = None) -> List[Any]:
        out: List[Any] = []
        for bundle in self._execute():
            block = ca.get(bundle.ref)
            out.extend(BlockAccessor.for_block(block).iter_rows())
            if limit is not None and len(out) > limit:
                raise ValueError(f"dataset has more than {limit} rows")
        return out

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy") -> Any:
        blocks = []
        rows = 0
        for bundle in self.limit(batch_size)._execute():
            blocks.append(ca.get(bundle.ref))
            rows += bundle.num_rows
            if rows >= batch_size:
                break
        if not blocks:
            return {}
        acc = BlockAccessor.for_block(BlockAccessor.concat(blocks))
        return BlockAccessor.for_block(acc.slice(0, min(batch_size, acc.num_rows()))).to_batch(
            batch_format
        )

    def show(self, limit: int = 20):
        for row in self.take(limit):
            print(row)

    def count(self) -> int:
        return sum(b.num_rows for b in self._execute())

    def num_blocks(self) -> int:
        return sum(1 for _ in self._execute())

    def size_bytes(self) -> int:
        return sum(b.size_bytes for b in self._execute())

    def columns(self) -> Optional[List[str]]:
        sch = self.schema()
        return list(sch.names) if sch is not None and hasattr(sch, "names") else None

    def schema(self):
        for bundle in self.limit(1)._execute():
            block = ca.get(bundle.ref)
            return BlockAccessor.for_block(block).schema()
        return None

    def stats(self) -> str:
        return self._stats.summary()

    # ----------------------------------------------------------- iteration
    def iterator(self) -> "DataIterator":
        from .iterator import DataIterator

        return DataIterator(self)

    def iter_rows(self) -> Iterator[Any]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_jax_batches(**kw)

    # ----------------------------------------------------------------- split
    def split(self, n: int, *, equal: bool = False) -> List["MaterializedDataset"]:
        mat = self.materialize()
        total = sum(b.num_rows for b in mat._bundles)
        if equal:
            per = total // n
            indices = [per * i for i in range(1, n)]
        else:
            indices = [(total * i) // n for i in range(1, n)]
        parts = self._split_at(mat._bundles, indices, truncate=total - (total // n) * n if equal else 0)
        return [MaterializedDataset(p, self._stats) for p in parts]

    def split_at_indices(self, indices: List[int]) -> List["MaterializedDataset"]:
        mat = self.materialize()
        parts = self._split_at(mat._bundles, list(indices))
        return [MaterializedDataset(p, self._stats) for p in parts]

    def split_proportionately(self, proportions: List[float]) -> List["MaterializedDataset"]:
        if not proportions or any(p <= 0 for p in proportions) or sum(proportions) >= 1:
            raise ValueError("proportions must be positive and sum to < 1")
        mat = self.materialize()
        total = mat.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            indices.append(int(total * acc))
        return mat.split_at_indices(indices)

    def train_test_split(
        self, test_size: float, *, shuffle: bool = False, seed: Optional[int] = None
    ) -> Tuple["MaterializedDataset", "MaterializedDataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()  # single execution: count + split reuse blocks
        total = mat.count()
        split = int(total * (1 - test_size))
        train, test = mat.split_at_indices([split])
        return train, test

    @staticmethod
    def _split_at(bundles: List[RefBundle], indices: List[int], truncate: int = 0):
        from .executor import _select_range, _slice_concat

        bounds = [0] + sorted(indices)
        total = sum(b.num_rows for b in bundles)
        bounds.append(total - truncate if truncate else total)
        parts = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            need = _select_range(bundles, lo, hi)
            aligned = [
                bundles[i]
                for (i, s, e) in need
                if s == 0 and e == bundles[i].num_rows
            ]
            if len(aligned) == len(need):  # no block straddles the boundary
                parts.append(aligned)
                continue
            ranges = [r[1:] for r in need]
            refs = [bundles[r[0]].ref for r in need]
            block_ref, meta_ref = _slice_concat.options(num_returns=2).remote(ranges, *refs)
            meta = ca.get(meta_ref)
            parts.append([RefBundle(block_ref, meta["num_rows"], meta["size_bytes"])])
        return parts

    def streaming_split(self, n: int, *, equal: bool = False) -> List["DataIterator"]:
        return [s.iterator() for s in self.split(n, equal=equal)]

    # ---------------------------------------------------------------- writes
    def _write(self, path: str, file_format: str, **kw) -> List[str]:
        from .datasource import write_block

        @ca.remote
        def write(block, index):
            return write_block(block, path, file_format, index, **kw)

        refs = [
            write.remote(b.ref, i) for i, b in enumerate(self._execute())
        ]
        return ca.get(refs)

    def write_parquet(self, path: str, **kw) -> List[str]:
        return self._write(path, "parquet", **kw)

    def write_csv(self, path: str, **kw) -> List[str]:
        return self._write(path, "csv", **kw)

    def write_json(self, path: str, **kw) -> List[str]:
        return self._write(path, "json", **kw)

    def write_numpy(self, path: str, *, column: Optional[str] = None) -> List[str]:
        return self._write(path, "npy", column=column)

    # ------------------------------------------------------------ converters
    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        frames = []
        for bundle in self._execute():
            frames.append(BlockAccessor.for_block(ca.get(bundle.ref)).to_pandas())
        if not frames:
            return pd.DataFrame()
        out = pd.concat(frames, ignore_index=True)
        if limit is not None and len(out) > limit:
            raise ValueError(f"dataset has more than {limit} rows")
        return out

    def to_arrow_refs(self) -> List[Any]:
        return [b.ref for b in self._execute()]

    def to_numpy_refs(self) -> List[Any]:
        @ca.remote
        def conv(block):
            return BlockAccessor.for_block(block).to_numpy_batch()

        return [conv.remote(b.ref) for b in self._execute()]

    # ------------------------------------------------------------- aggregates
    def aggregate(self, *aggs) -> Dict[str, Any]:
        return self.groupby(None).aggregate(*aggs).take(1)[0]

    def sum(self, on: str):
        from .aggregate import Sum

        return self.aggregate(Sum(on))[f"sum({on})"]

    def min(self, on: str):
        from .aggregate import Min

        return self.aggregate(Min(on))[f"min({on})"]

    def max(self, on: str):
        from .aggregate import Max

        return self.aggregate(Max(on))[f"max({on})"]

    def mean(self, on: str):
        from .aggregate import Mean

        return self.aggregate(Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        from .aggregate import Std

        return self.aggregate(Std(on, ddof=ddof))[f"std({on})"]

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        if not 0 <= fraction <= 1:
            raise ValueError("fraction must be in [0, 1]")

        def sample(batch):
            import zlib

            import numpy as np

            from .block import BlockAccessor, build_block

            acc = BlockAccessor.for_block(build_block(batch))
            n = max(0, round(acc.num_rows() * fraction))
            block_seed = seed
            if seed is not None:
                # derive a per-block seed from the data: a single seed would
                # pick identical row positions in every equal-sized block
                first = np.asarray(next(iter(batch.values()), np.array([])))
                raw = (
                    first.tobytes()[:4096]
                    if first.dtype != object
                    else str(first[:16]).encode()
                )
                token = zlib.crc32(raw)
                block_seed = np.random.SeedSequence([seed, token]).generate_state(1)[0]
            return BlockAccessor.for_block(acc.sample_rows(n, block_seed)).to_numpy_batch()

        return self.map_batches(sample, batch_format="numpy")

    def unique(self, column: str) -> List[Any]:
        rows = self.groupby(column).count().take_all()
        return [r[column] for r in rows]

    def __repr__(self):
        return f"Dataset(plan={self._plan!r})"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are computed and held by refs (analogue of
    ray.data.MaterializedDataset)."""

    def __init__(self, bundles: List[RefBundle], stats: Optional[ExecStats] = None):
        super().__init__(LogicalPlan([InputData(bundles)]))
        self._bundles = bundles
        if stats is not None:
            self._stats = stats

    def count(self) -> int:
        return sum(b.num_rows for b in self._bundles)

    def num_blocks(self) -> int:
        return len(self._bundles)

    def size_bytes(self) -> int:
        return sum(b.size_bytes for b in self._bundles)

    def materialize(self) -> "MaterializedDataset":
        return self


def _named(name: str, fn=None):
    def f():
        raise RuntimeError("placeholder; handled by transform kind")

    f.__name__ = name if fn is None else f"{name}:{getattr(fn, '__name__', 'fn')}"
    return f
