"""Block representation + accessor.

A *block* is the unit of parallelism in the data library (analogue of the
reference's python/ray/data/block.py BlockAccessor over Arrow/pandas blocks).
Two physical layouts:

- ``pyarrow.Table`` — the default for tabular/tensor data; zero-copy column
  access, cheap slicing/concat, efficient shm transit.
- ``list`` of arbitrary Python rows — fallback for heterogeneous objects.

``BlockAccessor.for_block`` dispatches on the layout.  All transforms accept
and return *batches* (dict[str, np.ndarray], pandas.DataFrame, pyarrow.Table,
or list of rows) and the accessor converts at the edges.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is available in the image
    pa = None

Block = Union["pa.Table", List[Any]]

# column name used when the user provides scalar items (mirrors the
# reference's "item" column for simple datasets)
ITEM_COL = "item"


def _is_tensor_like(col: np.ndarray) -> bool:
    return isinstance(col, np.ndarray) and col.ndim > 1


class _TensorArray:
    """Minimal fixed-shape tensor column for Arrow tables: stored as a
    FixedSizeListArray with shape metadata (analogue of the reference's
    ArrowTensorArray, python/ray/air/util/tensor_extensions/arrow.py)."""

    @staticmethod
    def to_arrow(col: np.ndarray):
        flat = np.ascontiguousarray(col).reshape(len(col), -1)
        inner = pa.array(flat.ravel())
        fsl = pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])
        return fsl, col.shape[1:]

    @staticmethod
    def from_arrow(arr, shape) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        values = arr.values.to_numpy(zero_copy_only=False)
        return values.reshape((len(arr),) + tuple(shape))


def build_block(batch: Any) -> Block:
    """Normalize any supported batch format into a block."""
    if pa is not None and isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, list):
        return batch
    if isinstance(batch, dict):
        return _table_from_numpy_dict(batch)
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, np.ndarray):
        return _table_from_numpy_dict({"data": batch})
    raise TypeError(f"cannot build a block from {type(batch)}")


def _table_from_numpy_dict(d: Dict[str, Any]) -> "pa.Table":
    cols, names, meta = [], [], {}
    for name, col in d.items():
        col = np.asarray(col)
        if _is_tensor_like(col):
            arr, shape = _TensorArray.to_arrow(col)
            meta[f"tensor:{name}"] = repr(list(shape))
            cols.append(arr)
        elif col.dtype == object:
            cols.append(pa.array(col.tolist()))
        else:
            cols.append(pa.array(col))
        names.append(name)
    t = pa.table(dict(zip(names, cols)))
    if meta:
        t = t.replace_schema_metadata(
            {**(t.schema.metadata or {}), **{k.encode(): v.encode() for k, v in meta.items()}}
        )
    return t


class BlockAccessor:
    """Uniform view over a block (analogue of ray.data.block.BlockAccessor)."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ------------------------------------------------------------- structure
    def num_rows(self) -> int:
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block.num_rows
        return len(self._block)

    def size_bytes(self) -> int:
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block.nbytes
        try:
            import sys

            return sum(sys.getsizeof(r) for r in self._block)
        except Exception:
            return 0

    def schema(self):
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block.schema
        if self._block:
            return type(self._block[0])
        return None

    def slice(self, start: int, end: int) -> Block:
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block.slice(start, end - start)
        return self._block[start:end]

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0] or list(blocks[:1])
        if not blocks:
            return []
        if pa is not None and isinstance(blocks[0], pa.Table):
            meta = {}
            for b in blocks:
                if b.schema.metadata:
                    meta.update(b.schema.metadata)
            t = pa.concat_tables(blocks, promote_options="default")
            if meta:
                t = t.replace_schema_metadata({**meta, **(t.schema.metadata or {})})
            return t
        out: List[Any] = []
        for b in blocks:
            out.extend(b)
        return out

    # ----------------------------------------------------------- conversion
    def _tensor_shapes(self) -> Dict[str, tuple]:
        meta = self._block.schema.metadata or {}
        out = {}
        for k, v in meta.items():
            k = k.decode()
            if k.startswith("tensor:"):
                out[k[len("tensor:"):]] = tuple(eval(v.decode()))  # noqa: S307 - own metadata
        return out

    def to_numpy_batch(self) -> Dict[str, np.ndarray]:
        if pa is not None and isinstance(self._block, pa.Table):
            shapes = self._tensor_shapes()
            out = {}
            for name in self._block.column_names:
                col = self._block.column(name)
                if name in shapes:
                    out[name] = _TensorArray.from_arrow(col, shapes[name])
                else:
                    out[name] = col.to_numpy(zero_copy_only=False)
            return out
        # list block: rows must be dicts for a columnar view
        if self._block and isinstance(self._block[0], dict):
            keys = self._block[0].keys()
            return {k: np.asarray([r[k] for r in self._block]) for k in keys}
        return {ITEM_COL: np.asarray(self._block, dtype=object)}

    def to_arrow(self) -> "pa.Table":
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block
        return _table_from_numpy_dict(self.to_numpy_batch())

    def to_pandas(self):
        import pandas as pd

        if pa is not None and isinstance(self._block, pa.Table):
            shapes = self._tensor_shapes()
            if shapes:
                batch = self.to_numpy_batch()
                return pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in batch.items()})
            return self._block.to_pandas()
        return pd.DataFrame(self.to_numpy_batch())

    def to_batch(self, batch_format: Optional[str]) -> Any:
        if batch_format in (None, "default", "numpy"):
            return self.to_numpy_batch()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        if batch_format == "rows":
            return list(self.iter_rows())
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ------------------------------------------------------------------ rows
    def iter_rows(self) -> Iterator[Any]:
        if pa is not None and isinstance(self._block, pa.Table):
            shapes = self._tensor_shapes()
            cols = {name: self._block.column(name) for name in self._block.column_names}
            simple = set(self._block.column_names) == {ITEM_COL} and not shapes
            np_cols = {
                name: _TensorArray.from_arrow(c, shapes[name]) if name in shapes else None
                for name, c in cols.items()
            }
            for i in range(self._block.num_rows):
                row = {}
                for name, c in cols.items():
                    if np_cols[name] is not None:
                        row[name] = np_cols[name][i]
                    else:
                        row[name] = c[i].as_py()
                yield row[ITEM_COL] if simple else row
        else:
            yield from self._block

    def sample_rows(self, n: int, seed: Optional[int] = None) -> Block:
        rng = np.random.default_rng(seed)
        total = self.num_rows()
        idx = rng.choice(total, size=min(n, total), replace=False)
        return self.take_indices(np.sort(idx))

    def take_indices(self, idx) -> Block:
        if pa is not None and isinstance(self._block, pa.Table):
            return self._block.take(pa.array(np.asarray(idx, dtype=np.int64)))
        return [self._block[int(i)] for i in idx]
