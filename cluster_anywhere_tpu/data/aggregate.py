"""Aggregations (analogue of python/ray/data/aggregate.py AggregateFn and the
sort-based groupby in python/ray/data/_internal/planner/exchange/).

All aggregations are vectorized over numpy columns within a partition; the
executor hash-partitions rows by key so each group lives wholly in one
partition.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .block import Block, BlockAccessor, build_block


class AggregateFn:
    name: str = "agg"

    def __init__(self, on: Optional[str] = None, alias_name: Optional[str] = None):
        self.on = on
        if alias_name:
            self.name = alias_name

    def compute(self, values: np.ndarray) -> Any:
        raise NotImplementedError

    def out_name(self) -> str:
        return self.name if not self.on else f"{self.name}({self.on})"


class Count(AggregateFn):
    name = "count"

    def compute(self, values):
        return len(values)

    def out_name(self) -> str:
        return "count()"


class Sum(AggregateFn):
    name = "sum"

    def compute(self, values):
        return values.sum() if len(values) else 0


class Min(AggregateFn):
    name = "min"

    def compute(self, values):
        return values.min() if len(values) else None


class Max(AggregateFn):
    name = "max"

    def compute(self, values):
        return values.max() if len(values) else None


class Mean(AggregateFn):
    name = "mean"

    def compute(self, values):
        return float(values.mean()) if len(values) else None


class Std(AggregateFn):
    name = "std"

    def __init__(self, on=None, ddof: int = 1, alias_name=None):
        super().__init__(on, alias_name)
        self.ddof = ddof

    def compute(self, values):
        if len(values) <= self.ddof:
            return None
        return float(values.std(ddof=self.ddof))


class AbsMax(AggregateFn):
    name = "abs_max"

    def compute(self, values):
        return np.abs(values).max() if len(values) else None


class Quantile(AggregateFn):
    name = "quantile"

    def __init__(self, on=None, q: float = 0.5, alias_name=None):
        super().__init__(on, alias_name)
        self.q = q

    def compute(self, values):
        return float(np.quantile(values, self.q)) if len(values) else None


def aggregate_block(block: Block, key: Optional[str], aggs: List[AggregateFn]) -> Block:
    """Group rows of `block` by `key` (or globally if None) and apply aggs."""
    acc = BlockAccessor.for_block(block)
    batch = acc.to_numpy_batch() if acc.num_rows() else {}
    if key is None:
        row: Dict[str, Any] = {}
        for agg in aggs:
            col = batch.get(agg.on, np.array([])) if agg.on else _first_col(batch)
            row[agg.out_name()] = agg.compute(np.asarray(col))
        return build_block({k: np.asarray([v]) for k, v in row.items()})
    if not batch:
        return []
    keys = batch[key]
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    out: Dict[str, list] = {key: list(uniq)}
    for agg in aggs:
        col = batch[agg.on] if agg.on else keys
        col = col[order]
        vals = []
        bounds = list(starts) + [len(col)]
        for i in range(len(uniq)):
            vals.append(agg.compute(np.asarray(col[bounds[i] : bounds[i + 1]])))
        out[agg.out_name()] = vals
    return build_block({k: np.asarray(v) for k, v in out.items()})


def _first_col(batch: Dict[str, np.ndarray]) -> np.ndarray:
    for v in batch.values():
        return v
    return np.array([])
