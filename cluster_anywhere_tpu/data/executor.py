"""Streaming executor: drives the logical plan over the cluster with bounded
in-flight tasks (compact analogue of the reference's
python/ray/data/_internal/execution/streaming_executor.py).

Execution model:
- the plan is compiled into *segments*: [source] -> fused map chain ->
  (barrier all-to-all) -> fused map chain -> ...
- map segments stream: one remote task per block, at most `max_in_flight`
  outstanding (backpressure), results yielded in submission order;
- all-to-all segments materialize their input bundles, then run a 2-phase
  remote shuffle (partition tasks with num_returns=N, then N merge tasks).

A bundle is (block_ref, meta) where meta = {"num_rows": int, "size_bytes": int}.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core import api as ca
from .block import Block, BlockAccessor, build_block
from .plan import AllToAll, InputData, Limit, LogicalPlan, MapLike, Read, ReadIterator, UnionOp, ZipOp


class RefBundle:
    __slots__ = ("ref", "num_rows", "size_bytes")

    def __init__(self, ref, num_rows: int, size_bytes: int):
        self.ref = ref
        self.num_rows = num_rows
        self.size_bytes = size_bytes


class ExecStats:
    def __init__(self):
        self.stages: List[Dict[str, Any]] = []

    def add(self, name: str, wall_s: float, blocks: int, rows: int):
        self.stages.append(
            {"stage": name, "wall_s": round(wall_s, 4), "blocks": blocks, "rows": rows}
        )

    def summary(self) -> str:
        lines = []
        for s in self.stages:
            lines.append(
                f"Stage {s['stage']}: {s['blocks']} blocks, {s['rows']} rows, "
                f"{s['wall_s']}s"
            )
        return "\n".join(lines) or "(not executed)"


# ----------------------------------------------------------- remote task fns


def _apply_chain(chain: List[Dict[str, Any]], block: Block) -> Block:
    """Apply a fused chain of map-like transforms to one block."""
    from .transform import apply_transform

    blocks = [block]
    for spec in chain:
        out: List[Block] = []
        for b in blocks:
            out.extend(apply_transform(spec, b))
        blocks = out
    if not blocks:
        return []
    return BlockAccessor.concat(blocks)


def _gen_blocks(gen_fn, rows_per_block: int):
    """Streaming-read driver: run the user generator on a worker, batch its
    rows into blocks, and yield (meta, block) pairs as streamed returns."""
    import numpy as np

    from .block import ITEM_COL, BlockAccessor, build_block

    def emit(rows):
        if rows and all(isinstance(r, dict) for r in rows):
            keys = list(rows[0].keys())
            if all(list(r.keys()) == keys for r in rows):
                return build_block({k: np.asarray([r[k] for r in rows]) for k in keys})
        try:
            return build_block({ITEM_COL: np.asarray(rows)})
        except Exception:
            return rows

    buf: List[Any] = []
    for row in gen_fn():
        buf.append(row)
        if len(buf) >= rows_per_block:
            block = emit(buf)
            acc = BlockAccessor.for_block(block)
            yield {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}
            yield block
            buf = []
    if buf:
        block = emit(buf)
        acc = BlockAccessor.for_block(block)
        yield {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}
        yield block


def _read_and_map(read_task, chain: List[Dict[str, Any]]):
    blocks = []
    for b in read_task():
        blocks.append(_apply_chain(chain, b) if chain else b)
    block = BlockAccessor.concat(blocks) if blocks else []
    acc = BlockAccessor.for_block(block)
    return block, {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


def _map_block(chain: List[Dict[str, Any]], block: Block):
    out = _apply_chain(chain, block)
    if isinstance(out, list) and not out:
        # preserve the input schema on fully-filtered blocks
        out = BlockAccessor.for_block(block).slice(0, 0)
    acc = BlockAccessor.for_block(out)
    return out, {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


class _MapWorker:
    """Actor for class-based UDFs (reference: ActorPoolMapOperator)."""

    def __init__(self, chain: List[Dict[str, Any]]):
        from .transform import instantiate_callables

        self.chain = instantiate_callables(chain)

    def ready(self):
        return "ok"

    def apply(self, block: Block):
        return _map_block(self.chain, block)


# ------------------------------------------------------------------ executor


def _cluster_cpus() -> int:
    try:
        return int(ca.cluster_resources().get("CPU", 4))
    except Exception:
        return 4


class StreamingExecutor:
    def __init__(self, plan: LogicalPlan, stats: Optional[ExecStats] = None):
        self.plan = plan
        self.stats = stats or ExecStats()

    # -- public -------------------------------------------------------------
    def execute(self) -> Iterator[RefBundle]:
        segments = self._compile(self.plan)
        stream: Iterator[RefBundle] = iter(())
        for seg in segments:
            stream = seg(stream)
        return stream

    # -- compilation ---------------------------------------------------------
    def _compile(self, plan: LogicalPlan) -> List[Callable]:
        from .transform import to_spec

        segments: List[Callable] = []
        i = 0
        ops = plan.ops
        while i < len(ops):
            op = ops[i]
            if isinstance(op, (Read, InputData)):
                # fuse following resource-free task-compute maps into the read
                chain, i2 = self._collect_chain(ops, i + 1)
                if chain and not chain[0].is_actor and (
                    chain[0].num_cpus or chain[0].num_tpus
                ):
                    chain, i2 = [], i + 1
                segments.append(self._source_segment(op, chain))
                i = i2
            elif isinstance(op, MapLike):
                chain, i2 = self._collect_chain(ops, i)
                segments.append(self._map_segment(chain))
                i = i2
            elif isinstance(op, ReadIterator):
                segments.append(self._iterator_segment(op))
                i += 1
            elif isinstance(op, AllToAll):
                segments.append(self._all_to_all_segment(op))
                i += 1
            elif isinstance(op, Limit):
                segments.append(self._limit_segment(op.n))
                i += 1
            elif isinstance(op, UnionOp):
                segments.append(self._union_segment(op))
                i += 1
            elif isinstance(op, ZipOp):
                segments.append(self._zip_segment(op))
                i += 1
            else:
                raise TypeError(f"unknown op {op}")
        return segments

    def _collect_chain(self, ops, i) -> Tuple[List[MapLike], int]:
        """Collect a run of task-compute MapLike ops (fusable). Actor-compute
        ops and resource-spec changes break fusion (an op requesting TPUs must
        not be fused into a CPU-shaped task)."""
        chain: List[MapLike] = []
        while i < len(ops) and isinstance(ops[i], MapLike):
            op = ops[i]
            if op.is_actor:
                if not chain:
                    chain.append(op)
                    i += 1
                break
            if chain and (op.num_cpus, op.num_tpus) != (
                chain[0].num_cpus,
                chain[0].num_tpus,
            ):
                break
            chain.append(op)
            i += 1
        return chain, i

    # -- segments -------------------------------------------------------------
    def _source_segment(self, op, chain: List[MapLike]) -> Callable:
        from .transform import to_spec

        specs = [to_spec(m) for m in chain if not m.is_actor]
        actor_ops = [m for m in chain if m.is_actor]

        def run(_: Iterator[RefBundle]) -> Iterator[RefBundle]:
            t0 = time.monotonic()
            if isinstance(op, InputData):
                if specs:
                    yield from self._run_map_tasks(
                        iter(op.bundles), specs, None, f"{op.name}+map"
                    )
                else:
                    yield from op.bundles
                return
            parallelism = op.parallelism if op.parallelism > 0 else _cluster_cpus() * 2
            tasks = op.datasource.get_read_tasks(parallelism)
            name = op.name + ("+map" if specs else "")
            remote_read = ca.remote(_read_and_map).options(num_returns=2)
            thunks = deque(
                (lambda rt=rt: remote_read.remote(rt, specs)) for rt in tasks
            )
            yield from self._drive(thunks, name, t0)

        if actor_ops:
            inner = run
            actor_seg = self._map_segment(actor_ops)
            return lambda stream: actor_seg(inner(stream))
        return run

    def _iterator_segment(self, op) -> Callable:
        """Streaming-generator source: ONE remote task yields blocks with
        producer-side backpressure; the consumer pulls them through
        iter_batches at its own pace (ObjectRefGenerator wiring)."""

        def run(_: Iterator[RefBundle]) -> Iterator[RefBundle]:
            t0 = time.monotonic()
            gen = ca.remote(_gen_blocks).options(num_returns="streaming").remote(
                op.gen_fn, op.rows_per_block
            )
            rows = nblocks = 0
            it = iter(gen)
            for meta_ref in it:
                block_ref = next(it)
                meta = ca.get(meta_ref)
                rows += meta["num_rows"]
                nblocks += 1
                yield RefBundle(block_ref, meta["num_rows"], meta["size_bytes"])
            self.stats.add(op.name, time.monotonic() - t0, nblocks, rows)

        return run

    def _map_segment(self, chain: List[MapLike]) -> Callable:
        from .transform import to_spec

        name = "+".join(m.name for m in chain)
        if chain[0].is_actor:
            op = chain[0]
            return lambda stream: self._run_actor_map(stream, op, name)
        specs = [to_spec(m) for m in chain]
        opts = {}
        if chain[0].num_cpus:
            opts["num_cpus"] = chain[0].num_cpus
        if chain[0].num_tpus:
            opts["num_tpus"] = chain[0].num_tpus
        return lambda stream: self._run_map_tasks(stream, specs, opts or None, name)

    def _run_map_tasks(self, stream, specs, opts, name) -> Iterator[RefBundle]:
        t0 = time.monotonic()
        remote_map = ca.remote(_map_block).options(num_returns=2, **(opts or {}))

        def thunk_iter():
            for bundle in stream:
                yield lambda b=bundle: remote_map.remote(specs, b.ref)

        yield from self._drive_lazy(thunk_iter(), name, t0)

    def _run_actor_map(self, stream, op: MapLike, name) -> Iterator[RefBundle]:
        from .transform import to_spec

        t0 = time.monotonic()
        n = op.concurrency or 2
        spec = to_spec(op)
        opts: Dict[str, Any] = {}
        if op.num_cpus:
            opts["num_cpus"] = op.num_cpus
        if op.num_tpus:
            opts["num_tpus"] = op.num_tpus
        Worker = ca.remote(_MapWorker)
        if opts:
            Worker = Worker.options(**opts)
        actors = [Worker.remote([spec]) for _ in range(n)]
        ca.get([a.ready.remote() for a in actors])
        # round-robin with at most 2 in-flight per actor
        inflight: deque = deque()
        per_actor: Dict[int, int] = {i: 0 for i in range(n)}
        rows = blocks = 0

        def pick_actor() -> Optional[int]:
            free = [i for i, c in per_actor.items() if c < 2]
            return min(free, key=lambda i: per_actor[i]) if free else None

        stream = iter(stream)
        exhausted = False
        try:
            while True:
                while not exhausted:
                    i = pick_actor()
                    if i is None:
                        break
                    bundle = next(stream, None)
                    if bundle is None:
                        exhausted = True
                        break
                    refs = actors[i].apply.options(num_returns=2).remote(bundle.ref)
                    per_actor[i] += 1
                    inflight.append((i, refs))
                if not inflight:
                    break
                i, (block_ref, meta_ref) = inflight.popleft()
                meta = ca.get(meta_ref)
                per_actor[i] -= 1
                rows += meta["num_rows"]
                blocks += 1
                yield RefBundle(block_ref, meta["num_rows"], meta["size_bytes"])
        finally:
            # also reached via GeneratorExit when the consumer stops early
            # (limit/take) — the pool must not leak worker processes
            from ..core.actor import kill

            for a in actors:
                try:
                    kill(a)
                except Exception:
                    pass
            self.stats.add(name, time.monotonic() - t0, blocks, rows)

    def _drive(self, thunks: deque, name: str, t0: float) -> Iterator[RefBundle]:
        yield from self._drive_lazy(iter(list(thunks)), name, t0)

    def _drive_lazy(self, thunk_iter, name: str, t0: float) -> Iterator[RefBundle]:
        """Submit thunks with bounded in-flight; yield in submission order."""
        max_in_flight = _cluster_cpus() * 2
        inflight: deque = deque()
        rows = blocks = 0
        exhausted = False
        while True:
            while not exhausted and len(inflight) < max_in_flight:
                thunk = next(thunk_iter, None)
                if thunk is None:
                    exhausted = True
                    break
                inflight.append(thunk())
            if not inflight:
                break
            block_ref, meta_ref = inflight.popleft()
            meta = ca.get(meta_ref)
            rows += meta["num_rows"]
            blocks += 1
            yield RefBundle(block_ref, meta["num_rows"], meta["size_bytes"])
        self.stats.add(name, time.monotonic() - t0, blocks, rows)

    def _limit_segment(self, n: int) -> Callable:
        def run(stream: Iterator[RefBundle]) -> Iterator[RefBundle]:
            remaining = n
            for bundle in stream:
                if bundle.num_rows <= remaining:
                    remaining -= bundle.num_rows
                    yield bundle
                else:
                    ref, meta_ref = _slice_task.options(num_returns=2).remote(
                        bundle.ref, remaining
                    )
                    meta = ca.get(meta_ref)
                    remaining = 0
                    yield RefBundle(ref, meta["num_rows"], meta["size_bytes"])
                if remaining <= 0:
                    break  # close upstream immediately: no further submissions

        return run

    def _union_segment(self, op: UnionOp) -> Callable:
        def run(stream: Iterator[RefBundle]) -> Iterator[RefBundle]:
            yield from stream
            for other in op.others:
                yield from StreamingExecutor(other, self.stats).execute()

        return run

    def _zip_segment(self, op: ZipOp) -> Callable:
        def run(stream: Iterator[RefBundle]) -> Iterator[RefBundle]:
            left = list(stream)
            right = list(StreamingExecutor(op.other, self.stats).execute())
            lrows = sum(b.num_rows for b in left)
            rrows = sum(b.num_rows for b in right)
            if lrows != rrows:
                raise ValueError(f"zip row-count mismatch: {lrows} vs {rrows}")
            # align right to left's block boundaries
            offsets = []
            off = 0
            for b in left:
                offsets.append((off, off + b.num_rows))
                off += b.num_rows
            for lb, (start, end) in zip(left, offsets):
                need = _select_range(right, start, end)
                ranges = [r[1:] for r in need]
                refs = [right[r[0]].ref for r in need]
                ref, meta_ref = _zip_task.options(num_returns=2).remote(
                    lb.ref, ranges, *refs
                )
                meta = ca.get(meta_ref)
                yield RefBundle(ref, meta["num_rows"], meta["size_bytes"])

        return run

    # -- all-to-all -----------------------------------------------------------
    def _all_to_all_segment(self, op: AllToAll) -> Callable:
        def run(stream: Iterator[RefBundle]) -> Iterator[RefBundle]:
            t0 = time.monotonic()
            all_bundles = list(stream)
            bundles = [b for b in all_bundles if b.num_rows > 0] or all_bundles[:1]
            if not bundles:  # upstream yielded nothing at all
                self.stats.add(op.kind, time.monotonic() - t0, 0, 0)
                return
            kind = op.kind
            if kind == "repartition":
                out = self._repartition(bundles, op.options["num_blocks"])
            elif kind == "random_shuffle":
                out = self._random_shuffle(bundles, op.options.get("seed"))
            elif kind == "sort":
                out = self._sort(bundles, op.options["key"], op.options.get("descending", False))
            elif kind == "aggregate":
                out = self._aggregate(bundles, op.options["key"], op.options["aggs"])
            elif kind == "randomize_block_order":
                rng = np.random.default_rng(op.options.get("seed"))
                out = [bundles[i] for i in rng.permutation(len(bundles))]
            else:
                raise ValueError(f"unknown all-to-all {kind}")
            rows = sum(b.num_rows for b in out)
            self.stats.add(kind, time.monotonic() - t0, len(out), rows)
            yield from out

        return run

    def _collect(self, pairs) -> List[RefBundle]:
        out = []
        for block_ref, meta_ref in pairs:
            meta = ca.get(meta_ref)
            out.append(RefBundle(block_ref, meta["num_rows"], meta["size_bytes"]))
        return out

    def _repartition(self, bundles: List[RefBundle], n: int) -> List[RefBundle]:
        total = sum(b.num_rows for b in bundles)
        splits = [(total * i) // n for i in range(n + 1)]
        pairs = []
        for j in range(n):
            start, end = splits[j], splits[j + 1]
            need = _select_range(bundles, start, end)
            ranges = [r[1:] for r in need]
            refs = [bundles[r[0]].ref for r in need]
            pairs.append(_slice_concat.options(num_returns=2).remote(ranges, *refs))
        return self._collect(pairs)

    def _random_shuffle(self, bundles, seed) -> List[RefBundle]:
        n = max(1, len(bundles))
        parts: List[List] = [[] for _ in range(n)]
        for i, b in enumerate(bundles):
            s = None if seed is None else seed + i
            refs = _shuffle_partition.options(num_returns=n).remote(b.ref, n, s)
            if n == 1:
                refs = [refs]
            for j, r in enumerate(refs):
                parts[j].append(r)
        pairs = []
        for j in range(n):
            s = None if seed is None else seed * 100003 + j
            pairs.append(_concat_shuffle.options(num_returns=2).remote(s, *parts[j]))
        return self._collect(pairs)

    def _sort(self, bundles, key, descending) -> List[RefBundle]:
        n = max(1, len(bundles))
        if n == 1:
            pairs = [_merge_sorted.options(num_returns=2).remote(key, descending, bundles[0].ref)]
            return self._collect(pairs)
        samples = ca.get([_sample_key.remote(b.ref, key, 64) for b in bundles])
        allv = np.concatenate([s for s in samples if len(s)]) if samples else np.array([])
        allv.sort()
        qs = [(len(allv) * i) // n for i in range(1, n)]
        boundaries = [allv[q] for q in qs] if len(allv) else []
        parts: List[List] = [[] for _ in range(n)]
        for b in bundles:
            refs = _range_partition.options(num_returns=n).remote(
                b.ref, key, boundaries, descending
            )
            if n == 1:
                refs = [refs]
            for j, r in enumerate(refs):
                parts[j].append(r)
        order = range(n - 1, -1, -1) if descending else range(n)
        pairs = [
            _merge_sorted.options(num_returns=2).remote(key, descending, *parts[j])
            for j in order
        ]
        return self._collect(pairs)

    def _aggregate(self, bundles, key, aggs) -> List[RefBundle]:
        n = max(1, min(len(bundles), 16))
        if key is None:
            n = 1
        parts: List[List] = [[] for _ in range(n)]
        for b in bundles:
            refs = _hash_partition.options(num_returns=n).remote(b.ref, key, n)
            if n == 1:
                refs = [refs]
            for j, r in enumerate(refs):
                parts[j].append(r)
        pairs = [
            _agg_partition.options(num_returns=2).remote(key, aggs, *parts[j])
            for j in range(n)
        ]
        out = self._collect(pairs)
        if key is not None:
            out = [b for b in out if b.num_rows > 0] or out[:1]
        return out


def _select_range(bundles: List[RefBundle], start: int, end: int):
    """Which (bundle_idx, local_start, local_end) cover global rows [start,end)."""
    out = []
    off = 0
    for i, b in enumerate(bundles):
        b_start, b_end = off, off + b.num_rows
        lo, hi = max(start, b_start), min(end, b_end)
        if lo < hi:
            out.append((i, lo - b_start, hi - b_start))
        off = b_end
    return out


# ------------------------------------------------------- remote helper tasks


def _meta(block: Block):
    acc = BlockAccessor.for_block(block)
    return block, {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


@ca.remote
def _slice_task(block: Block, n: int):
    return _meta(BlockAccessor.for_block(block).slice(0, n))


@ca.remote
def _slice_concat(ranges, *blocks):
    parts = [
        BlockAccessor.for_block(b).slice(s, e) for b, (s, e) in zip(blocks, ranges)
    ]
    return _meta(BlockAccessor.concat(parts) if parts else [])


@ca.remote
def _zip_task(left: Block, ranges, *rights):
    lacc = BlockAccessor.for_block(left)
    rparts = [BlockAccessor.for_block(b).slice(s, e) for b, (s, e) in zip(rights, ranges)]
    right = BlockAccessor.concat(rparts) if rparts else []
    lt, rt = lacc.to_arrow(), BlockAccessor.for_block(right).to_arrow()
    meta = dict(lt.schema.metadata or {})
    rmeta = rt.schema.metadata or {}
    for name in rt.column_names:
        out_name = name if name not in lt.column_names else name + "_1"
        lt = lt.append_column(out_name, rt.column(name))
        shape = rmeta.get(f"tensor:{name}".encode())
        if shape is not None:
            meta[f"tensor:{out_name}".encode()] = shape
    if meta:
        lt = lt.replace_schema_metadata(meta)
    return _meta(lt)


@ca.remote
def _shuffle_partition(block: Block, n: int, seed):
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n, size=rows)
    outs = []
    for j in range(n):
        idx = np.nonzero(assign == j)[0]
        outs.append(acc.take_indices(idx))
    return tuple(outs) if n > 1 else outs[0]


@ca.remote
def _concat_shuffle(seed, *parts):
    block = BlockAccessor.concat(list(parts)) if parts else []
    acc = BlockAccessor.for_block(block)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(acc.num_rows())
    return _meta(acc.take_indices(perm))


@ca.remote
def _sample_key(block: Block, key: str, n: int):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy_batch()[key]
    if len(col) == 0:
        return col
    rng = np.random.default_rng(0)
    return col[rng.choice(len(col), size=min(n, len(col)), replace=False)]


@ca.remote
def _range_partition(block: Block, key: str, boundaries, descending: bool):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy_batch()[key]
    n = len(boundaries) + 1
    assign = np.searchsorted(np.asarray(boundaries), col, side="right")
    outs = []
    for j in range(n):
        idx = np.nonzero(assign == j)[0]
        outs.append(acc.take_indices(idx))
    return tuple(outs) if n > 1 else outs[0]


@ca.remote
def _merge_sorted(key: str, descending: bool, *parts):
    block = BlockAccessor.concat(list(parts)) if parts else []
    acc = BlockAccessor.for_block(block)
    if acc.num_rows() == 0:
        return _meta(block)
    col = acc.to_numpy_batch()[key]
    order = np.argsort(col, kind="stable")
    if descending:
        order = order[::-1]
    return _meta(acc.take_indices(order))


def _stable_hash(x) -> int:
    """Deterministic across processes (hash() of str/bytes is per-process
    randomized, which would scatter one key over several partitions)."""
    import zlib

    if isinstance(x, (int, np.integer)):
        return int(x)
    if isinstance(x, bytes):
        return zlib.crc32(x)
    return zlib.crc32(str(x).encode())


@ca.remote
def _hash_partition(block: Block, key, n: int):
    acc = BlockAccessor.for_block(block)
    if key is None or n == 1:
        return _meta_free(acc, n)
    col = acc.to_numpy_batch()[key]
    hashes = np.asarray([_stable_hash(x) % n for x in col.tolist()], dtype=np.int64)
    outs = []
    for j in range(n):
        idx = np.nonzero(hashes == j)[0]
        outs.append(acc.take_indices(idx))
    return tuple(outs) if n > 1 else outs[0]


def _meta_free(acc, n):
    outs = [acc._block] + [acc.slice(0, 0) for _ in range(n - 1)]
    return tuple(outs) if n > 1 else outs[0]


@ca.remote
def _agg_partition(key, aggs, *parts):
    from .aggregate import aggregate_block

    block = BlockAccessor.concat(list(parts)) if parts else []
    return _meta(aggregate_block(block, key, aggs))
