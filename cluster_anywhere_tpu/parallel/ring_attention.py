"""Ring attention: exact attention over sequence shards with O(T/sp) memory
per device and compute/communication overlap.

The reference provides no sequence parallelism (SURVEY.md §5: "SP/CP not
implemented in-tree"); this module is part of closing that gap TPU-natively.
Each device holds a sequence shard of Q, K, V.  K/V blocks rotate around the
'sp' mesh axis via `lax.ppermute` while every device accumulates its Q-shard's
attention with streaming (flash-style) softmax, so the full [T, T] score
matrix never materializes.

On TPU each arriving block is processed by the Pallas flash kernel
(ops.attention.flash_attention) — full attention for blocks from earlier
shards, causal for the diagonal block, skipped for future shards — and the
per-block (out, lse) partials are combined with ops.attention.merge_attention.
On CPU test meshes (or non-tiling shapes) the same schedule runs as a pure
jnp streaming-softmax loop; both paths are differentiable.

Usage inside shard_map (manual over 'sp'; see tests/test_parallel.py):
    out = ring_attention(q, k, v, axis_name="sp", causal=True)
with q, k, v shaped [batch, seq_shard, heads, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, flash_attention, merge_attention


def _block_attention(q, k, v, scale, mask, m_prev, l_prev, o_prev):
    """One streaming-softmax accumulation step.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; mask: [Tq, Tk] bool (True=keep)
    m, l: [B, H, Tq]; o: [B, Tq, H, D]
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_blk)
    # guard fully-masked rows: keep exp() finite
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    alpha = jnp.exp(m_prev - m_new)  # [B, H, Tq]
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o_prev * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _flash_tiles(t_local: int) -> bool:
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    return platform not in ("cpu",) and t_local >= 128 and t_local % 128 == 0


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Exact attention over a ring of sequence shards (call inside shard_map).

    Shapes (per device): q, k, v: [B, T_local, H, D] -> out [B, T_local, H, D].
    For GQA repeat K/V heads to H before calling.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    if use_flash is None:
        use_flash = _flash_tiles(t_local)
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale, n, my_idx)

    m0 = jnp.full((b, h, t_local), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t_local), dtype=jnp.float32)
    o0 = jnp.zeros((b, t_local, h, d), dtype=jnp.float32)

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_pos = jnp.arange(t_local)

    def step(carry, step_idx):
        k_blk, v_blk, m, l, o = carry
        # the block arriving at step s originated at device (my_idx - s) mod n
        src = (my_idx - step_idx) % n
        if causal:
            q_pos = my_idx * t_local + local_pos  # global query positions
            k_pos = src * t_local + local_pos
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        m, l, o = _block_attention(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            scale, mask, m, l, o,
        )
        # rotate k/v to the next device; skip the final (wasted) rotation
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (_, _, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0), jnp.arange(n))
    # final normalization; fully-masked rows (l==0) return 0
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name, causal, scale, n, my_idx):
    """Flash-kernel ring schedule: per arriving K/V block run the Pallas
    kernel in the right causality mode and merge the (out, lse) partials.
    Blocks from later shards contribute nothing under causal masking and are
    skipped via lax.switch (the branch still participates in the merge with
    lse=-inf, i.e. zero weight)."""
    b, t_local, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _full(q, kb, vb):
        o, lse = flash_attention(q, kb, vb, causal=False, scale=scale, return_lse=True)
        return o.astype(jnp.float32), lse

    def _causal(q, kb, vb):
        o, lse = flash_attention(q, kb, vb, causal=True, scale=scale, return_lse=True)
        return o.astype(jnp.float32), lse

    def _skip(q, kb, vb):
        return (
            jnp.zeros((b, t_local, h, d), jnp.float32),
            jnp.full((b, h, t_local), NEG_INF, jnp.float32),
        )

    o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t_local), NEG_INF, jnp.float32)

    def step(carry, step_idx):
        k_blk, v_blk, o, lse = carry
        src = (my_idx - step_idx) % n
        if causal:
            # 0: future shard (skip), 1: diagonal (causal), 2: past (full)
            mode = jnp.where(src == my_idx, 1, jnp.where(src < my_idx, 2, 0))
        else:
            mode = 2
        ob, lb = lax.switch(mode, [_skip, _causal, _full], q, k_blk, v_blk)
        o, lse = merge_attention(o, lse, ob, lb)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, o, lse), None

    (_, _, o, _), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=True, use_flash=None):
    """Convenience wrapper: shard_map over the sp axis of `mesh` with
    [batch, seq, heads, dim] inputs sharded on seq."""
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(
        ring_attention, axis_name=axis_name, causal=causal, use_flash=use_flash
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


from ..ops.attention import reference_attention  # noqa: E402  (re-export; test oracle)
