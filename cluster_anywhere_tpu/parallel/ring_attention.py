"""Ring attention: exact attention over sequence shards with O(T/sp) memory
per device and compute/communication overlap.

The reference provides no sequence parallelism (SURVEY.md §5: "SP/CP not
implemented in-tree"); this module is part of closing that gap TPU-natively.
Each device holds a sequence shard of Q, K, V.  K/V blocks rotate around the
'sp' mesh axis via `lax.ppermute` while every device accumulates its Q-shard's
attention with streaming (flash-style) softmax: running max `m`, normalizer
`l`, and un-normalized output `o` are updated per block, so the full [T, T]
score matrix never materializes.  The loop is a `lax.scan` of pure jax ops —
differentiable by construction, and on TPU each block's inner attention can
dispatch to the Pallas flash kernel (ops.attention).

Usage inside shard_map (manual over 'sp'; see tests/test_parallel.py):
    out = ring_attention(q, k, v, axis_name="sp", causal=True)
with q, k, v shaped [batch, seq_shard, heads, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attention(q, k, v, scale, mask, m_prev, l_prev, o_prev):
    """One streaming-softmax accumulation step.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; mask: [Tq, Tk] bool (True=keep)
    m, l: [B, H, Tq]; o: [B, Tq, H, D]
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_blk)
    # guard fully-masked rows: keep exp() finite
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    alpha = jnp.exp(m_prev - m_new)  # [B, H, Tq]
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o_prev * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a ring of sequence shards (call inside shard_map).

    Shapes (per device): q, k, v: [B, T_local, H, D] -> out [B, T_local, H, D].
    For GQA repeat K/V heads to H before calling.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    m0 = jnp.full((b, h, t_local), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t_local), dtype=jnp.float32)
    o0 = jnp.zeros((b, t_local, h, d), dtype=jnp.float32)

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_pos = jnp.arange(t_local)

    def step(carry, step_idx):
        k_blk, v_blk, m, l, o = carry
        # the block arriving at step s originated at device (my_idx - s) mod n
        src = (my_idx - step_idx) % n
        if causal:
            q_pos = my_idx * t_local + local_pos  # global query positions
            k_pos = src * t_local + local_pos
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        m, l, o = _block_attention(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            scale, mask, m, l, o,
        )
        # rotate k/v to the next device; skip the final (wasted) rotation
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (_, _, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0), jnp.arange(n))
    # final normalization; fully-masked rows (l==0) return 0
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=True):
    """Convenience wrapper: shard_map over the sp axis of `mesh` with
    [batch, seq, heads, dim] inputs sharded on seq."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def reference_attention(q, k, v, causal=True, scale=None):
    """Dense reference for testing: [B, T, H, D] -> [B, T, H, D]."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
