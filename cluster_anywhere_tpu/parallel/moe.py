"""Expert parallelism: switch-style Mixture-of-Experts FFN with capacity-based
top-1 routing and all-to-all token exchange over the 'ep' mesh axis.

Dispatch/combine use STATIC-SHAPE scatter/gather on flat slot indices
(token n -> slot expert_idx[n] * capacity + position-within-expert), with
dropped tokens routed to one overflow row that is sliced away.  The classic
one-hot-einsum formulation ("nxc,ne->xce") is O(N·X·C·E) — at N=8k tokens,
4 experts, capacity 2.5k it spends ~2.5x the expert FFN's FLOPs on routing
alone and materialises [N, X, C] dispatch tensors (measured 3.4 s/step vs
0.1 s dense on v5e); the scatter form is O(N·E) with the same static
shapes, gradients, and all_to_all layout.  Experts' weights are sharded
over 'ep'; tokens travel to their expert's device via `lax.all_to_all`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class MoEOutput(NamedTuple):
    out: jax.Array
    aux_loss: jax.Array  # load-balancing loss (Switch Transformer style)


def moe_ffn(
    x: jax.Array,  # [N_local_tokens, E]
    router_w: jax.Array,  # [E, n_experts] (replicated)
    w_in: jax.Array,  # [local_experts, E, F]
    w_out: jax.Array,  # [local_experts, F, E]
    *,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
) -> MoEOutput:
    """Call inside shard_map (manual over `axis_name`)."""
    ep = lax.psum(1, axis_name)
    n_local, e_model = x.shape
    local_experts = w_in.shape[0]
    n_experts = ep * local_experts

    logits = x @ router_w  # [N, n_experts]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # top-1
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]  # [N]

    capacity = int(max(1, (n_local * capacity_factor) // n_experts + 1))
    # position of each token within its expert's queue (cumulative count of
    # same-expert tokens before it); int path — no [N, X, C] one-hots
    onehot_i = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [N, X]
    pos = jnp.take_along_axis(
        jnp.cumsum(onehot_i, axis=0) - 1, expert_idx[:, None], axis=-1
    )[:, 0]  # [N]
    keep = pos < capacity
    # flat slot: expert * capacity + position; dropped tokens go to the one
    # overflow row (X*C) that both sides discard
    slot = jnp.where(keep, expert_idx * capacity + pos, n_experts * capacity)
    expert_in = jnp.zeros((n_experts * capacity + 1, e_model), x.dtype)
    expert_in = expert_in.at[slot].set(x)  # unique slots: set, not add
    expert_in = expert_in[: n_experts * capacity]
    expert_in = expert_in.reshape(ep, local_experts, capacity, e_model)
    # each device receives, for its local experts, the token slots from every
    # source device: [ep_src, local_experts, C, E]
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0, concat_axis=0, tiled=False)
    expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
        local_experts, ep * capacity, e_model
    )

    h = jax.nn.silu(jnp.einsum("xne,xef->xnf", expert_in, w_in))
    expert_out = jnp.einsum("xnf,xfe->xne", h, w_out)

    # route back
    expert_out = expert_out.reshape(local_experts, ep, capacity, e_model).transpose(
        1, 0, 2, 3
    )
    expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0, concat_axis=0, tiled=False)
    expert_out = expert_out.reshape(n_experts * capacity, e_model)
    # combine: gather each token's slot back and gate it; dropped tokens
    # contribute zero (residual connection carries them unchanged upstream)
    out = jnp.take(expert_out, jnp.minimum(slot, n_experts * capacity - 1), axis=0)
    out = out * (gate * keep.astype(gate.dtype))[:, None]

    # load-balance aux loss: fraction routed * mean prob, summed over experts
    frac = jnp.mean(onehot_i.astype(probs.dtype), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = jnp.sum(frac * mean_prob) * n_experts
    return MoEOutput(out, aux)


def init_moe_params(key, e_model: int, f_hidden: int, n_experts: int, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = (2.0 / e_model) ** 0.5
    scale_out = (2.0 / f_hidden) ** 0.5
    return {
        "router": jax.random.normal(k1, (e_model, n_experts), dtype) * 0.02,
        "w_in": jax.random.normal(k2, (n_experts, e_model, f_hidden), dtype) * scale_in,
        "w_out": jax.random.normal(k3, (n_experts, f_hidden, e_model), dtype) * scale_out,
    }
