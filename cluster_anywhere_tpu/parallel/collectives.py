"""Collective communication API, mirroring the surface of the reference's
ray.util.collective (SURVEY.md §2.3: init_collective_group / allreduce /
allgather / reducescatter / broadcast / send-recv / barrier) with
TPU-native backends:

- "xla": in-graph collectives for device tensors — thin wrappers over
  lax.psum/all_gather/psum_scatter/ppermute for use inside jit/shard_map.
  On TPU these compile to ICI transfers; this is the fast tensor plane and
  replaces the reference's NCCL backend.
- "host" (default out-of-graph): PEER-TO-PEER collectives for host (numpy)
  data between processes — the Gloo-role backend
  (gloo_collective_group.py:184).  The head's KV carries ONE rendezvous
  record per rank (its serving address, at group init); after that every
  tensor byte moves worker-to-worker over direct connections: ring
  allreduce/allgather, direct-push broadcast and send/recv.  Nothing per-op
  lands on the head's loop (the r4 'data plane through head KV' weakness).
- "kv": the previous KV-rendezvous transport (refs through head KV, payload
  via the object store) — kept for remote clients, which cannot serve
  direct connections.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_groups: Dict[str, Any] = {}


# ---------------------------------------------------------------------------
# block quantization (EQuARX-style wire encodings for the host ring)
# ---------------------------------------------------------------------------
# Wire format (rides the coll_push `meta` dict, transport-agnostic):
#   int8: payload = int8 elements, row-major in blocks of `bk` elements
#         (last block zero-padded); meta = {"qm": "int8", "n": element
#         count, "bk": block size, "sc": one f32 LE scale per block —
#         value = q * scale, scale = max|block| / 127}.
#   bf16: payload = uint16 elements (f32 truncated to the upper 16 bits,
#         round-to-nearest-even); meta = {"qm": "bf16", "n": count}.
# Mixed-version ranks negotiate by construction: quantization is selected
# per CALL (or per group via config.collective_quantize), every rank of a
# group executes the same call, and a rank that cannot decode `qm` raises
# rather than silently reducing garbage.

QUANT_MODES = ("int8", "bf16")

# Accelerated encode/decode kernels.  The per-hop quantize/dequantize is
# the quantized ring's entire CPU cost (the wire savings come free), and
# separate numpy ufunc passes touch the chunk ~6 times; a fused XLA kernel
# (jax pinned to the HOST CPU backend — never the accelerator) does it in
# ~2 memory passes, and ml_dtypes casts bf16 at memcpy speed (its byte
# layout is exactly this wire format's RTNE truncation).  Both probe once
# and degrade to pure numpy, which stays the semantic reference.
_INT8_KERNELS: Any = None  # (encode, decode) | False once probed

try:
    import ml_dtypes as _ml_dtypes

    _BF16 = np.dtype(_ml_dtypes.bfloat16)
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None


def _int8_kernels():
    global _INT8_KERNELS
    if _INT8_KERNELS is None:
        try:
            from functools import partial

            import jax
            import jax.numpy as jnp

            @partial(jax.jit, static_argnums=(1,), backend="cpu")
            def _enc(flat, block):
                b = flat.reshape(-1, block)
                scale = jnp.max(jnp.abs(b), axis=1) / 127.0
                inv = jnp.where(
                    scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0
                )
                q = jnp.round(b * inv[:, None]).astype(jnp.int8)
                return q.reshape(-1), scale

            @partial(jax.jit, static_argnums=(2,), backend="cpu")
            def _dec(q, scale, block):
                return (
                    q.reshape(-1, block).astype(jnp.float32) * scale[:, None]
                ).reshape(-1)

            _INT8_KERNELS = (_enc, _dec)
        except Exception:
            _INT8_KERNELS = False
    return _INT8_KERNELS or None


def quantize_chunk(flat, mode: str, block: int) -> Tuple[bytes, dict]:
    """Encode a float vector for the wire.  Returns (payload, meta); the
    pair round-trips through dequantize_chunk with the documented error
    bound (int8: per element <= max|block| / 254 + float rounding)."""
    flat = np.ascontiguousarray(flat, dtype=np.float32).reshape(-1)
    n = flat.size
    if mode == "bf16":
        if _BF16 is not None:
            return flat.astype(_BF16).tobytes(), {"qm": "bf16", "n": n}
        u = flat.view(np.uint32)
        q = ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1)))
             >> np.uint32(16)).astype(np.uint16)
        return q.tobytes(), {"qm": "bf16", "n": n}
    if mode != "int8":
        raise ValueError(f"unsupported quantization mode {mode!r}")
    block = max(1, int(block))
    nb = (n + block - 1) // block
    padded = flat
    if nb * block != n:
        padded = np.zeros(nb * block, np.float32)
        padded[:n] = flat
    kern = _int8_kernels()
    if kern is not None and n:
        q, scale = kern[0](padded, block)
        return np.asarray(q).tobytes(), {
            "qm": "int8", "n": n, "bk": block,
            "sc": np.asarray(scale, dtype=np.float32).tobytes(),
        }
    b = padded.reshape(nb, block)
    scale = np.abs(b).max(axis=1)
    scale /= np.float32(127.0)
    inv = np.where(scale > 0.0, np.float32(1.0) / np.where(
        scale > 0.0, scale, np.float32(1.0)), np.float32(0.0))
    q = np.rint(b * inv[:, None]).astype(np.int8)
    return q.tobytes(), {
        "qm": "int8", "n": n, "bk": block,
        "sc": scale.astype(np.float32).tobytes(),
    }


def dequantize_chunk(payload: bytes, meta: dict) -> np.ndarray:
    """Decode a quantize_chunk wire pair back to float32."""
    qm = meta.get("qm")
    n = int(meta.get("n", 0))
    if qm == "bf16":
        if _BF16 is not None:
            return np.frombuffer(payload, dtype=_BF16)[:n].astype(np.float32)
        u = np.frombuffer(payload, dtype=np.uint16).astype(np.uint32)
        return (u << np.uint32(16)).view(np.float32)[:n]
    if qm == "int8":
        block = int(meta["bk"])
        scale = np.frombuffer(meta["sc"], dtype=np.float32)
        q = np.frombuffer(payload, dtype=np.int8)
        kern = _int8_kernels()
        if kern is not None and n:
            return np.asarray(kern[1](q, scale, block))[:n]
        out = (
            q.astype(np.float32).reshape(scale.size, block) * scale[:, None]
        ).reshape(-1)
        return out[:n]
    raise ValueError(
        f"peer sent unknown quantized payload {qm!r} — mixed-version group? "
        f"(this build decodes {QUANT_MODES})"
    )


def _resolve_quant(quantize: Optional[str]) -> Optional[str]:
    """Normalize a per-call/per-group quantize selector: None/''/'f32'/
    'none' = the untouched f32 path; 'int8'/'bf16' = quantized ring."""
    if quantize in (None, "", "f32", "none"):
        return None
    if quantize not in QUANT_MODES:
        raise ValueError(
            f"quantize must be one of {QUANT_MODES} (or None for f32), "
            f"got {quantize!r}"
        )
    return quantize


# ---------------------------------------------------------------------------
# host backend (Gloo-equivalent): KV-rendezvous reductions between processes
# ---------------------------------------------------------------------------


class HostCollectiveGroup:
    """Gloo-role host collectives (util/collective GLOOGroup analogue).

    The KV store carries only rendezvous metadata — pickled ObjectRefs, a
    few hundred bytes — while tensor payloads ride the object store's data
    plane: zero-copy shm between same-host ranks, chunked TCP pulls across
    nodes.  Reductions are rooted: every rank publishes one chunk, the root
    reduces and publishes one result, every other rank polls exactly one
    key — O(world) tensor movements per op, not the O(world^2) of all-ranks
    -fetch-all-chunks.
    """

    # refs published for recent ops are retained so a lagging consumer's
    # borrow registration always lands while the producer still holds the
    # object (SPMD lockstep bounds consumer lag to ~2 ops; 4 is margin)
    _RETAIN_OPS = 4

    def __init__(self, world_size: int, rank: int, group_name: str = "default"):
        from collections import deque

        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0
        self._live = deque(maxlen=self._RETAIN_OPS * max(world_size, 2))
        # (seq, ns, key) for every rooted-collective KV entry this rank
        # published; entries older than _RETAIN_OPS ops are kv_del'd so a
        # long run's rendezvous keys don't accumulate in the head's KV (and
        # its debounced snapshots).  p2p send keys are tracked separately:
        # they are consumed (and deleted) by recv, which is NOT lockstep-
        # bounded, so they must never be horizon-GC'd.
        self._published: deque = deque()
        # bounded: recv deletes consumed keys itself, so old entries here
        # are almost certainly already gone — the cap keeps a long-running
        # sender's bookkeeping (and close()'s kv_del sweep) O(1), at the
        # cost of not sweeping ancient unconsumed sends on close
        self._p2p_published: deque = deque(maxlen=512)
        # p2p sequence numbers are per-destination and independent of the
        # collective op counter: bumping the shared _seq on send() would
        # desynchronize the per-op rendezvous namespaces between ranks
        # that send and ranks that only recv
        self._p2p_seq: Dict[int, int] = {}

    def _ns(self, op: str) -> str:
        return f"__collective__/{self.group_name}/{self._seq}/{op}"

    def _kv(self):
        from ..core.worker import global_worker

        return global_worker()

    def _publish(self, ns: str, key: str, value: np.ndarray, p2p: bool = False):
        """ca.put the tensor; only the ref crosses the head's KV.  Small
        tensors put inline must be promoted to cluster-visible shm first —
        a ref smuggled through KV bypasses the task-arg promotion path."""
        from ..core import api as ca_api

        ref = ca_api.put(np.ascontiguousarray(value))
        self._kv()._promote_nested([ref.id.binary()])
        self._live.append(ref)
        self._kv().head_call("kv_put", ns=ns, key=key, value=pickle.dumps(ref))
        # rooted ops bump _seq before publishing, so the op being published
        # is _seq - 1; recording _seq itself would widen retention by one op
        (self._p2p_published if p2p else self._published).append(
            (self._seq if p2p else self._seq - 1, ns, key)
        )
        self._gc_published()

    def _gc_published(self):
        """Delete this rank's rooted rendezvous keys older than _RETAIN_OPS
        ops.  By then every peer has fetched (SPMD lockstep bounds lag), so
        the keys are dead weight in the head KV and every snapshot write."""
        w = self._kv()
        horizon = self._seq - self._RETAIN_OPS
        while self._published and self._published[0][0] < horizon:
            _, ns, key = self._published.popleft()
            try:
                w.head_call("kv_del", ns=ns, key=key)
            except Exception:
                pass  # head restart mid-run: stale keys die with the old KV

    def close(self):
        """Drop this rank's expired rendezvous keys and unconsumed p2p sends.
        Keys from the most recent _RETAIN_OPS rooted ops are deliberately
        left alive — a lagging peer may still be fetching them (barrier()
        before destroy for a fully clean teardown); at most _RETAIN_OPS
        keys per rank remain, bounded, not a leak-over-time.  A no-op after
        ca.shutdown (cleanup must stay safe in any teardown order)."""
        from ..core.worker import try_global_worker

        w = try_global_worker()
        if w is None:
            self._published.clear()
            self._p2p_published.clear()
            return
        for q in (self._published, self._p2p_published):
            while q:
                seq, ns, key = q.popleft()
                if q is self._published and seq >= self._seq - self._RETAIN_OPS:
                    continue
                try:
                    w.head_call("kv_del", ns=ns, key=key)
                except Exception:
                    return

    def _fetch(self, ns: str, key: str, timeout: float = 60.0) -> np.ndarray:
        """Poll one KV key for a ref, then read the payload from the store."""
        from ..core import api as ca_api

        w = self._kv()
        deadline = time.monotonic() + timeout
        while True:
            v = w.head_call("kv_get", ns=ns, key=key)["value"]
            if v is not None:
                return np.asarray(ca_api.get(pickle.loads(v)))
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective {ns}/{key} timed out")
            time.sleep(0.002)

    @staticmethod
    def _reduce(stack: np.ndarray, op: str) -> np.ndarray:
        if op == "sum":
            return stack.sum(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        if op == "mean":
            return stack.mean(axis=0)
        raise ValueError(f"unsupported op {op}")

    def allreduce(
        self, tensor: np.ndarray, op: str = "sum",
        quantize: Optional[str] = None,
    ) -> np.ndarray:
        if _resolve_quant(quantize) is not None:
            raise ValueError(
                "quantized allreduce needs the p2p 'host' transport; this "
                "group uses the 'kv' rendezvous backend (remote clients)"
            )
        ns = self._ns("allreduce")
        self._seq += 1
        if self.rank == 0:
            parts = [np.asarray(tensor)]
            for r in range(1, self.world_size):
                parts.append(self._fetch(ns, str(r)))
            result = self._reduce(np.stack(parts), op)
            if self.world_size > 1:
                self._publish(ns, "result", result)
            return result
        self._publish(ns, str(self.rank), np.asarray(tensor))
        return self._fetch(ns, "result")

    def allgather(self, tensor: np.ndarray) -> List[np.ndarray]:
        ns = self._ns("allgather")
        self._seq += 1
        self._publish(ns, str(self.rank), np.asarray(tensor))
        # every rank reads every chunk, but through the data plane (shm
        # locally), so the head only serves world_size tiny ref lookups
        return [self._fetch(ns, str(r)) for r in range(self.world_size)]

    def reducescatter(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        full = self.allreduce(tensor, op)
        return np.array_split(full, self.world_size)[self.rank]

    def broadcast(self, tensor: Optional[np.ndarray], src_rank: int = 0) -> np.ndarray:
        ns = self._ns("broadcast")
        self._seq += 1
        if self.rank == src_rank:
            arr = np.asarray(tensor)
            if self.world_size > 1:
                self._publish(ns, "0", arr)
            return arr
        return self._fetch(ns, "0")

    def barrier(self):
        self.allreduce(np.zeros(1))

    def send(self, tensor: np.ndarray, dst_rank: int):
        ns = f"__collective__/{self.group_name}/p2p/{self.rank}->{dst_rank}"
        k = self._p2p_seq.get(dst_rank, 0)
        self._p2p_seq[dst_rank] = k + 1
        self._publish(ns, str(k), np.asarray(tensor), p2p=True)

    def recv(self, src_rank: int, timeout: float = 60.0) -> np.ndarray:
        from ..core import api as ca_api

        ns = f"__collective__/{self.group_name}/p2p/{src_rank}->{self.rank}"
        w = self._kv()
        deadline = time.monotonic() + timeout
        while True:
            keys = sorted(w.head_call("kv_keys", ns=ns)["keys"], key=int)
            if keys:
                key = keys[0]
                v = w.head_call("kv_get", ns=ns, key=key)["value"]
                w.head_call("kv_del", ns=ns, key=key)
                return np.asarray(ca_api.get(pickle.loads(v)))
            if time.monotonic() > deadline:
                raise TimeoutError("recv timed out")
            time.sleep(0.002)


# ---------------------------------------------------------------------------
# p2p backend (Gloo role): direct worker-to-worker tensor movement
# ---------------------------------------------------------------------------


class P2PCollectiveGroup:
    """Host collectives whose tensor bytes move directly between the member
    processes (ring allreduce/allgather; direct-push broadcast/send/recv).

    The head KV holds exactly one record per rank — the rank's serving
    address, written once at init and deleted at close.  Every subsequent
    op is rank-to-rank RPC into a peer's collective mailbox
    (Worker.coll_deliver / coll_wait): zero per-op head traffic, unlike the
    KV transport this replaces (r4 weak #2).  Reference role:
    gloo_collective_group.py:184 (direct transport), redesigned over this
    runtime's existing worker duals instead of a separate Gloo context."""

    _TIMEOUT = 60.0

    def __init__(
        self, world_size: int, rank: int, group_name: str = "default",
        quantize: Optional[str] = None,
    ):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        # group-default wire encoding for allreduce: explicit arg wins, else
        # config.collective_quantize; per-CALL quantize= overrides both.
        # Must agree across ranks — it is part of the group contract, like
        # the backend (a mixed group would decode garbage; unknown modes
        # raise at the receiver).
        if quantize is None:
            from ..core.config import get_config

            quantize = getattr(get_config(), "collective_quantize", "") or None
        self._quantize = _resolve_quant(quantize)
        self._seq = 0
        self._p2p_send_seq: Dict[int, int] = {}
        self._p2p_recv_seq: Dict[int, int] = {}
        self._peer_addrs: Dict[int, str] = {}
        w = self._worker()
        if not (w.serve_addr or w.serve_addr_tcp or w._p2p_addr()):
            raise RuntimeError(
                "p2p collectives need a serving process (worker/actor/driver); "
                "remote clients should use backend='kv'"
            )
        # rendezvous record = this rank's CLIENT ID only; peers resolve it to
        # a dialable address through the head's p2p directory (client_addr),
        # which rewrites loopback/wildcard hosts per node — publishing raw
        # bound addresses here would hand cross-host peers 127.0.0.1
        self._members_ns = f"__collective__/{group_name}/members"
        w.head_call(
            "kv_put",
            ns=self._members_ns,
            key=str(rank),
            value=pickle.dumps({"client": w.client_id}),
        )

    def _worker(self):
        from ..core.worker import global_worker

        return global_worker()

    def _peer(self, rank: int) -> str:
        """Resolve (once) where a peer rank serves: poll the rendezvous KV
        for its client id, then the head's p2p directory for a dialable
        address (unix same-node, rewritten TCP cross-node)."""
        addr = self._peer_addrs.get(rank)
        if addr is not None:
            return addr
        w = self._worker()
        deadline = time.monotonic() + self._TIMEOUT
        while True:
            v = w.head_call("kv_get", ns=self._members_ns, key=str(rank))["value"]
            if v is not None:
                client = pickle.loads(v)["client"]
                addr = w._owner_addr(client)
                if addr is None:
                    raise RuntimeError(
                        f"rank {rank} (client {client}) of group "
                        f"{self.group_name!r} has no dialable p2p address — "
                        "every member of a 'host' group must be a serving "
                        "process; use backend='kv' for remote-client members"
                    )
                self._peer_addrs[rank] = addr
                return addr
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {rank} never joined group {self.group_name!r}"
                )
            time.sleep(0.005)

    # ------------------------------------------------------------- transport
    def _push(self, dst: int, key: str, arr: np.ndarray):
        self._worker().coll_push_to(
            self._peer(dst), self.group_name, key, self.rank, arr, self._TIMEOUT
        )

    def _push_start(self, dst: int, key: str, arr: np.ndarray):
        """Non-blocking send for the pipelined ring: serialize now, ship in
        the background, join via .result() after the overlapped receive."""
        return self._worker().coll_push_start(
            self._peer(dst), self.group_name, key, self.rank, arr, self._TIMEOUT
        )

    def _push_raw_start(self, dst: int, key: str, payload: bytes, meta: dict):
        return self._worker().coll_push_raw_start(
            self._peer(dst), self.group_name, key, self.rank, payload, meta,
            self._TIMEOUT,
        )

    def _wait(self, key: str, src: int) -> np.ndarray:
        return self._worker().coll_wait(
            self.group_name, key, src, self._TIMEOUT
        )

    def _wait_raw(self, key: str, src: int):
        return self._worker().coll_wait_raw(
            self.group_name, key, src, self._TIMEOUT
        )

    # ------------------------------------------------------------ collectives
    @staticmethod
    def _acc_dtype(dtype: np.dtype, op: str):
        # mirror the kv backend's np.stack(...).<op>(axis=0) result dtypes so
        # the two interchangeable backends agree bit-for-bit in type
        if op == "mean":
            return np.result_type(dtype, np.float64)
        if op == "sum":
            if np.issubdtype(dtype, np.unsignedinteger):
                return np.uint64  # np.sum keeps unsigned unsigned
            if dtype == np.bool_ or np.issubdtype(dtype, np.integer):
                return np.int64  # bools count (not saturate), ints widen like np.sum
        return dtype  # max/min (and float sum) preserve the input dtype

    @staticmethod
    def _combine(acc: np.ndarray, incoming: np.ndarray, op: str):
        if op in ("sum", "mean"):
            np.add(acc, incoming, out=acc)
        elif op == "max":
            np.maximum(acc, incoming, out=acc)
        elif op == "min":
            np.minimum(acc, incoming, out=acc)
        else:
            raise ValueError(f"unsupported op {op}")

    def allreduce(
        self, tensor: np.ndarray, op: str = "sum",
        quantize: Optional[str] = None,
    ) -> np.ndarray:
        """Ring allreduce, double-buffered: each step STARTS its send (bytes
        serialized before the call returns) and then blocks on the incoming
        chunk, so rank r's send of chunk i overlaps its receive of chunk i —
        instead of the strict send-ack-then-wait alternation.  quantize=
        "int8"/"bf16" selects the EQuARX-style block-quantized wire payload
        (per-call; the group/config default applies when omitted); the f32
        path below is bit-for-bit the untouched default."""
        arr = np.asarray(tensor)
        mode = self._quantize if quantize is None else _resolve_quant(quantize)
        if mode is not None:
            return self._allreduce_quantized(arr, op, mode)
        n = self.world_size
        self._seq += 1
        acc_dt = self._acc_dtype(arr.dtype, op)
        if n == 1:
            out = arr.astype(acc_dt, copy=True)  # mean of one = itself
            return self._mean_result_dtype(out, arr.dtype, op)
        seq = self._seq
        left, right = (self.rank - 1) % n, (self.rank + 1) % n
        flat = arr.astype(acc_dt).reshape(-1)
        chunks = [c.copy() for c in np.array_split(flat, n)]
        # ring reduce-scatter: after n-1 steps this rank holds the fully
        # reduced chunk (rank+1) % n
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            pend = self._push_start(right, f"{seq}/rs{s}", chunks[send_idx])
            incoming = self._wait(f"{seq}/rs{s}", src=left)
            self._combine(chunks[recv_idx], incoming.reshape(chunks[recv_idx].shape), op)
            pend.result(self._TIMEOUT)
        # ring allgather of the reduced chunks
        for s in range(n - 1):
            send_idx = (self.rank + 1 - s) % n
            recv_idx = (self.rank - s) % n
            pend = self._push_start(right, f"{seq}/ag{s}", chunks[send_idx])
            chunks[recv_idx] = self._wait(f"{seq}/ag{s}", src=left).reshape(
                chunks[recv_idx].shape
            ).copy()
            pend.result(self._TIMEOUT)
        out = np.concatenate([c.reshape(-1) for c in chunks]).reshape(arr.shape)
        if op == "mean":
            out = out / n
        return self._mean_result_dtype(out, arr.dtype, op)

    def _allreduce_quantized(
        self, arr: np.ndarray, op: str, mode: str
    ) -> np.ndarray:
        """Block-quantized ring (EQuARX, arxiv 2506.17615): reduce-scatter
        quantizes each outgoing chunk (quantize-on-send), dequantizes the
        incoming one, reduces in f32, and requantizes at the next hop; the
        allgather phase quantizes each fully-reduced chunk ONCE at its
        owner and forwards the wire bytes verbatim, so every rank decodes
        identical values.  Wire bytes per hop: n/4 + 4/block scales (int8)
        or n/2 (bf16) versus the f32 ring's n bytes."""
        if not np.issubdtype(arr.dtype, np.floating):
            raise ValueError(
                f"quantized allreduce needs a floating tensor, got {arr.dtype}"
            )
        from ..core.config import get_config
        from ..core.worker import TRANSFER_STATS

        block = int(getattr(get_config(), "collective_quant_block", 4096))
        n = self.world_size
        self._seq += 1
        saved = 0
        if n == 1:
            payload, meta = quantize_chunk(arr.reshape(-1), mode, block)
            out = dequantize_chunk(payload, meta)  # same error model as n>1
            TRANSFER_STATS["quant_ops"] += 1
            return out.reshape(arr.shape).astype(arr.dtype, copy=False)
        seq = self._seq
        left, right = (self.rank - 1) % n, (self.rank + 1) % n
        flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
        chunks = [c.copy() for c in np.array_split(flat, n)]
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            payload, meta = quantize_chunk(chunks[send_idx], mode, block)
            saved += chunks[send_idx].nbytes - len(payload) - len(meta.get("sc", b""))
            pend = self._push_raw_start(right, f"{seq}/qrs{s}", payload, meta)
            pdata, pmeta = self._wait_raw(f"{seq}/qrs{s}", src=left)
            self._combine(chunks[recv_idx], dequantize_chunk(pdata, pmeta), op)
            pend.result(self._TIMEOUT)
        own = (self.rank + 1) % n
        payload, meta = quantize_chunk(chunks[own], mode, block)
        # adopt the decoded form locally so this rank's result matches what
        # every peer reconstructs from the forwarded bytes
        chunks[own] = dequantize_chunk(payload, meta)
        for s in range(n - 1):
            recv_idx = (self.rank - s) % n
            saved += 4 * int(meta.get("n", 0)) - len(payload) - len(meta.get("sc", b""))
            pend = self._push_raw_start(right, f"{seq}/qag{s}", payload, meta)
            payload, meta = self._wait_raw(f"{seq}/qag{s}", src=left)
            chunks[recv_idx] = dequantize_chunk(payload, meta)
            pend.result(self._TIMEOUT)
        out = np.concatenate(chunks)
        if op == "mean":
            out = out / n
        TRANSFER_STATS["quant_ops"] += 1
        TRANSFER_STATS["quant_bytes_saved"] += max(0, saved)
        return out.reshape(arr.shape).astype(arr.dtype, copy=False)

    @staticmethod
    def _mean_result_dtype(out: np.ndarray, in_dtype: np.dtype, op: str):
        # match the kv backend (np.stack(...).mean(axis=0)): mean preserves
        # an inexact input dtype and yields float64 for integers — the f64
        # ring accumulator must not leak into the result
        if op == "mean" and np.issubdtype(in_dtype, np.inexact):
            return out.astype(in_dtype)
        return out

    def allgather(self, tensor: np.ndarray) -> List[np.ndarray]:
        arr = np.ascontiguousarray(tensor)
        n = self.world_size
        self._seq += 1
        if n == 1:
            return [arr.copy()]
        seq = self._seq
        left, right = (self.rank - 1) % n, (self.rank + 1) % n
        got: Dict[int, np.ndarray] = {self.rank: arr}
        carry = arr
        for s in range(n - 1):  # ring pass-along (shapes may differ per rank)
            self._push(right, f"{seq}/ag{s}", carry)
            carry = self._wait(f"{seq}/ag{s}", src=left).copy()
            got[(self.rank - 1 - s) % n] = carry
        return [got[r] for r in range(n)]

    def reducescatter(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        full = self.allreduce(tensor, op)
        return np.array_split(full, self.world_size)[self.rank]

    def broadcast(self, tensor: Optional[np.ndarray], src_rank: int = 0) -> np.ndarray:
        self._seq += 1
        seq = self._seq
        if self.rank == src_rank:
            arr = np.ascontiguousarray(tensor)
            for r in range(self.world_size):
                if r != self.rank:
                    self._push(r, f"{seq}/bc", arr)
            return np.asarray(tensor)
        return self._wait(f"{seq}/bc", src=src_rank).copy()

    def barrier(self):
        self.allreduce(np.zeros(1))

    def send(self, tensor: np.ndarray, dst_rank: int):
        k = self._p2p_send_seq.get(dst_rank, 0)
        self._p2p_send_seq[dst_rank] = k + 1
        self._push(dst_rank, f"p2p/{k}", np.asarray(tensor))

    def recv(self, src_rank: int, timeout: float = 60.0) -> np.ndarray:
        k = self._p2p_recv_seq.get(src_rank, 0)
        self._p2p_recv_seq[src_rank] = k + 1
        return self._worker().coll_wait(
            self.group_name, f"p2p/{k}", src_rank, timeout
        ).copy()

    def close(self):
        """Drop this rank's rendezvous record and any unconsumed mailbox
        entries.  Safe after ca.shutdown (any teardown order)."""
        from ..core.worker import try_global_worker

        w = try_global_worker()
        if w is None:
            return
        w.coll_clear(self.group_name)
        try:
            w.head_call("kv_del", ns=self._members_ns, key=str(self.rank))
        except Exception:
            pass


# ---------------------------------------------------------------------------
# public API (reference-parity surface)
# ---------------------------------------------------------------------------


def init_collective_group(
    world_size: int, rank: int, backend: str = "host",
    group_name: str = "default", quantize: Optional[str] = None,
):
    """backend='host'/'gloo': p2p transport (direct worker-to-worker bytes).
    backend='kv': the KV-rendezvous transport (required when ANY member is a
    remote client, which cannot serve direct connections).  The backend is
    per-GROUP, never per-rank: a silent per-rank fallback would build a
    mixed-transport group whose halves share no rendezvous and deadlock.
    `quantize` sets the group's default allreduce wire encoding (see
    allreduce; must agree across ranks, like the backend)."""
    if backend not in ("host", "gloo", "kv"):
        raise ValueError(
            "out-of-graph groups support the 'host' (p2p) and 'kv' backends; "
            "device tensors use in-graph xla collectives "
            "(cluster_anywhere_tpu.parallel.collectives.xla)"
        )
    if backend == "kv":
        if _resolve_quant(quantize) is not None:
            raise ValueError(
                "quantized collectives need the p2p 'host' backend"
            )
        g: Any = HostCollectiveGroup(world_size, rank, group_name)
    else:
        from ..core.worker import global_worker

        w = global_worker()
        if w.client_mode or not (
            w.serve_addr or w.serve_addr_tcp or w._p2p_addr()
        ):
            raise RuntimeError(
                "this rank cannot serve the p2p 'host' transport (remote "
                "client / no listener); create the WHOLE group with "
                "backend='kv' instead — transports cannot be mixed within "
                "a group"
            )
        g = P2PCollectiveGroup(world_size, rank, group_name, quantize=quantize)
    _groups[group_name] = g
    return g


def create_collective_group(
    actors,
    world_size: int,
    ranks: List[int],
    backend: str = "host",
    group_name: str = "default",
):
    """Declarative setup (reference: util/collective/collective.py:151
    create_collective_group): tells each actor to init_collective_group with
    its rank.  Actors must define
    `collective_init(self, world_size, rank, backend, group_name)` that calls
    `init_collective_group` (mixin: CollectiveActorMixin)."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    if len(set(ranks)) != len(ranks) or not all(0 <= r < world_size for r in ranks):
        raise ValueError(
            f"ranks must be unique and in [0, {world_size}); got {ranks}"
        )
    from ..core import api as ca

    refs = [
        a.collective_init.remote(world_size, r, backend, group_name)
        for a, r in zip(actors, ranks)
    ]
    ca.get(refs)


class CollectiveActorMixin:
    """Inherit in an actor class to make it usable with create_collective_group."""

    def collective_init(self, world_size, rank, backend="host", group_name="default"):
        init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def collective_close(self, group_name="default"):
        """Teardown hook: kv_del this rank's rendezvous record + drop its
        mailbox entries.  Call it before killing the actor — ca.kill alone
        leaks the member record into the head KV (and its snapshots), and a
        later group reusing the name could resolve a dead rank's address."""
        destroy_collective_group(group_name)
        return True


def destroy_group_on(actors, group_name: str = "default"):
    """Close `group_name` on every member actor (the teardown twin of
    create_collective_group)."""
    from ..core import api as ca

    ca.get(
        [a.collective_close.remote(group_name) for a in actors], timeout=30
    )


def get_group(group_name: str = "default"):
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return _groups[group_name]


def destroy_collective_group(group_name: str = "default"):
    g = _groups.pop(group_name, None)
    if g is not None:
        g.close()


def allreduce(
    tensor, op: str = "sum", group_name: str = "default",
    quantize: Optional[str] = None,
):
    """quantize="int8"/"bf16" selects the block-quantized ring payload for
    this call (p2p 'host' groups only); None defers to the group's default
    (init arg / config.collective_quantize), which itself defaults to the
    exact f32 wire path."""
    return get_group(group_name).allreduce(tensor, op, quantize=quantize)


def allgather(tensor, group_name: str = "default"):
    return get_group(group_name).allgather(tensor)


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    return get_group(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(tensor, src_rank)


def barrier(group_name: str = "default"):
    return get_group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default"):
    return get_group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return get_group(group_name).recv(src_rank)


# ---------------------------------------------------------------------------
# xla backend: in-graph device collectives (use inside jit / shard_map)
# ---------------------------------------------------------------------------


def quantized_psum(
    x, axis_name: str, quantize: str = "int8", block: int = 2048
):
    """In-graph quantized gradient sync (EQuARX analogue for the tensor
    plane), CPU-testable under JAX_PLATFORMS=cpu (works under vmap/shard_map
    axis names).

    int8: each rank block-quantizes its contribution once (per-block f32
    scales), ranks exchange the INT8 payloads (all_gather moves world x 1
    byte per element per link vs psum's ~2 x 4 bytes — a wire win up to
    world ~8) plus the tiny scale vectors, and every rank dequantize-sums
    locally — so the result is sum_r Dq(Q(x_r)), the same error model as
    the host quantized ring.  bf16: psum over bf16-cast operands (half the
    wire bytes, bf16 accumulation).  quantize=None/'f32' is exact psum."""
    from jax import lax
    import jax.numpy as jnp

    mode = _resolve_quant(quantize)
    if mode is None:
        return lax.psum(x, axis_name)
    if mode == "bf16":
        return lax.psum(x.astype(jnp.bfloat16), axis_name).astype(x.dtype)
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.size
    block = max(1, min(int(block), max(n, 1)))
    nb = max(1, (n + block - 1) // block)
    pad = nb * block - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    b = flat.reshape(nb, block)
    scale = jnp.max(jnp.abs(b), axis=1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    inv = jnp.where(scale > 0, 1.0 / safe, 0.0)
    q = jnp.round(b * inv[:, None]).astype(jnp.int8)
    qs = lax.all_gather(q, axis_name)      # [world, nb, block] int8 wire
    ss = lax.all_gather(scale, axis_name)  # [world, nb] f32 scales (tiny)
    out = (qs.astype(jnp.float32) * ss[:, :, None]).sum(axis=0).reshape(-1)
    if pad:
        out = out[:n]
    return out.reshape(shape).astype(x.dtype)


class xla:
    """In-graph collectives over mesh axes — the TPU tensor plane."""

    @staticmethod
    def allreduce(x, axis_name: str):
        from jax import lax

        return lax.psum(x, axis_name)

    # quantized gradient sync (module-level quantized_psum re-exported)
    quantized_psum = staticmethod(quantized_psum)

    @staticmethod
    def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
        from jax import lax

        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

    @staticmethod
    def reducescatter(x, axis_name: str, axis: int = 0, tiled: bool = True):
        from jax import lax

        return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)

    @staticmethod
    def broadcast(x, axis_name: str, src_index: int = 0):
        from jax import lax
        import jax.numpy as jnp

        idx = lax.axis_index(axis_name)
        return lax.psum(jnp.where(idx == src_index, x, jnp.zeros_like(x)), axis_name)

    @staticmethod
    def permute(x, axis_name: str, perm):
        from jax import lax

        return lax.ppermute(x, axis_name, perm)

    @staticmethod
    def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
        from jax import lax

        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
        )
