"""First-class parallelism strategies over jax device meshes.

DP/FSDP/TP via GSPMD sharding annotations (mesh.py, sharding.py), PP via
shard_map ppermute schedules (pipeline.py), SP/CP via ring attention
(ring_attention.py) and Ulysses all-to-all (ulysses.py), EP via switch-style
MoE with all-to-all routing (moe.py), plus a reference-parity collective API
(collectives.py).
"""

from .mesh import AXES, MeshSpec, auto_spec, local_mesh, make_mesh
from .sharding import DEFAULT_RULES, P, constraint, logical_to_spec, named_sharding, shard_pytree

__all__ = [
    "AXES",
    "MeshSpec",
    "auto_spec",
    "local_mesh",
    "make_mesh",
    "DEFAULT_RULES",
    "P",
    "constraint",
    "logical_to_spec",
    "named_sharding",
    "shard_pytree",
]
