"""jax API compatibility shims.

jax >= 0.6 exposes `jax.shard_map(..., axis_names=..., check_vma=...)`;
older runtimes (some containers ship 0.4.x) only have
`jax.experimental.shard_map.shard_map(..., auto=..., check_rep=...)`.
One wrapper normalizes the new-style call onto whichever is installed so
the parallel/ and models/ stacks run on both.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, axis_names=None, check_vma=False):
    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {"check_rep": check_vma}
    if axis_names is not None:
        # old API expresses partial-manual as the COMPLEMENT set
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
