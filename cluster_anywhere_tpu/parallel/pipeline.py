"""Pipeline parallelism: GPipe-style microbatch schedule over the 'pp' mesh
axis, expressed as a shard_map + lax.scan + ppermute program.

The reference reaches pipeline parallelism only through its compiled-graph
scheduler pushing per-actor operation lists (SURVEY.md §2.3 aDAG); here the
schedule is a compiled XLA program: every device runs its stage every step,
activations hop stage->stage+1 over ICI via ppermute, and the M+n-1 step loop
(bubble included) is a single lax.scan that XLA pipelines.  Differentiable by
construction — the backward pass is the transposed schedule.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pp",
    num_microbatches: int,
    with_aux: bool = False,
):
    """Run a stage-partitioned function over microbatches (call inside
    shard_map, manual over `axis_name`).

    stage_fn(params_of_my_stage, activ) -> activ, same shape/dtype (uniform
    stages).  x: [B, ...] (replicated across pp); returns [B, ...] with every
    stage holding the final output (psum broadcast).

    with_aux=True: stage_fn returns (activ, aux_scalar) — an auxiliary loss
    per microbatch per stage (MoE load balance).  Bubble steps (a stage fed
    zeros before/after its real work) are masked out; the result is the
    per-microbatch mean, summed over stages, so it matches what the
    unpipelined stack would have computed over the full batch.  Returns
    (out, aux)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} not divisible by num_microbatches {m}")
    micro = x.reshape(m, batch // m, *x.shape[1:])

    total_steps = m + n - 1
    buf0 = jnp.zeros_like(micro[0])
    outs0 = jnp.zeros_like(micro)
    # stage i -> i+1; stage 0 receives zeros (no wraparound source)
    fwd_perm = [(i, i + 1) for i in range(n - 1)]

    def step(carry, t):
        prev, outs, aux_acc = carry
        incoming = lax.ppermute(prev, axis_name, fwd_perm)
        mb = lax.dynamic_index_in_dim(micro, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        x_t = jnp.where(idx == 0, mb, incoming)
        if with_aux:
            y, aux = stage_fn(stage_params, x_t)
            # stage idx holds microbatch (t - idx) at step t; real work only
            # for 0 <= t - idx < m — everything else is pipeline bubble
            valid = jnp.logical_and(t >= idx, t - idx < m)
            aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
        else:
            y = stage_fn(stage_params, x_t)
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        updated = lax.dynamic_update_slice(
            outs, y[None].astype(outs.dtype), (out_idx,) + (0,) * y.ndim
        )
        write = jnp.logical_and(idx == n - 1, t >= n - 1)
        outs = jnp.where(write, updated, outs)
        return (y, outs, aux_acc), None

    (_, outs, aux_acc), _ = lax.scan(
        step, (buf0, outs0, jnp.zeros((), jnp.float32)), jnp.arange(total_steps)
    )
    # only the last stage holds real outputs; broadcast to every stage so the
    # loss (computed replicated over pp) sees them
    outs = lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    out = outs.reshape(batch, *x.shape[1:])
    if with_aux:
        # sum over stages (layers are partitioned over pp), mean over
        # microbatches — the unpipelined equivalent computes one aux over
        # the whole batch, which the per-microbatch mean estimates exactly
        # for batch-linear aux terms
        return out, lax.psum(aux_acc, axis_name) / m
    return out


def pipeline_sharded(stage_fn, mesh, *, axis_name="pp", num_microbatches):
    """Wrap pipeline_apply in shard_map: stage_params must be stacked with a
    leading pp axis (params[i] = stage i); x replicated."""
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map

    def inner(stacked_params, x):
        my_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
        return pipeline_apply(
            stage_fn, my_params, x, axis_name=axis_name, num_microbatches=num_microbatches
        )

    def apply(stacked_params, x):
        in_param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(in_param_specs, P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, x)

    return apply


def num_pipeline_stages(mesh, axis_name: str = "pp") -> int:
    return mesh.shape[axis_name]
