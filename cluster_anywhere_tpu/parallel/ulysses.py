"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

Alternative to ring attention for models where heads >= sp: re-shard
[B, T/sp, H, D] -> [B, T, H/sp, D] with one all-to-all, run *full-sequence*
attention on the local head subset, then all-to-all back.  Two collectives
per attention call instead of sp ppermutes; wins when T is moderate and H
is divisible by the sp axis.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from ..ops.attention import attention as _dispatch_attention


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = True, attn_fn=None):
    """Call inside shard_map. q,k,v: [B, T_local, H, D] (heads complete,
    sequence sharded). Requires H % sp == 0.

    Default attention over the gathered full sequence goes through the
    dispatcher: the Pallas flash kernel on TPU whenever the (full) sequence
    tiles, jnp reference otherwise."""
    n = lax.psum(1, axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(f"heads {q.shape[2]} not divisible by {axis_name}={n}")
    if attn_fn is None:
        attn_fn = functools.partial(_dispatch_attention, causal=causal)

    def scatter_heads(x):
        # [B, T/sp, H, D] -> [B, T, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def gather_heads(x):
        # [B, T, H/sp, D] -> [B, T/sp, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attn_fn(qh, kh, vh)
    return gather_heads(out)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sp", causal=True):
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
