"""Device mesh construction with canonical parallelism axes.

The reference leaves parallelism strategy to external libraries (SURVEY.md
§2.3: Ray supplies placement groups + collectives and defers DP/TP/PP/SP/EP
to torch/vLLM/DeepSpeed).  Here the strategies are first-class: every model
and train step in this framework is expressed over a `jax.sharding.Mesh` with
the canonical axis names below, and XLA compiles the collectives onto ICI.

Axes (size 1 = disabled, always present so PartitionSpecs are stable):
  dp    data parallel (gradient allreduce)
  fsdp  fully-sharded data parallel (params/opt-state sharded, allgather at use)
  pp    pipeline parallel (stage-partitioned layers, ppermute microbatches)
  tp    tensor parallel (matmul-sharded, allreduce/allgather activations)
  sp    sequence/context parallel (ring attention / Ulysses all-to-all)
  ep    expert parallel (MoE experts sharded, all-to-all token routing)
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Optional, Sequence

AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        n = 1
        for f in fields(self):
            n *= getattr(self, f.name)
        return n

    def axis_sizes(self):
        return tuple(getattr(self, a) for a in AXES)

    def __str__(self):
        return "x".join(f"{a}{getattr(self, a)}" for a in AXES if getattr(self, a) > 1) or "single"


def make_mesh(spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None, **axes):
    """Build a Mesh over `devices` (default: all) shaped by `spec`.

    Axis ordering follows AXES with dp outermost — adjacent mesh dims map to
    adjacent devices, so the innermost axes (tp/sp/ep, which carry the most
    collective traffic) land on nearest-neighbour ICI links.
    """
    import jax
    import numpy as np

    if spec is None:
        spec = MeshSpec(**axes)
    elif axes:
        raise ValueError("pass either a MeshSpec or axis kwargs, not both")
    if devices is None:
        devices = jax.devices()
    if spec.size != len(devices):
        raise ValueError(
            f"mesh spec {spec} needs {spec.size} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(spec.axis_sizes())
    return jax.sharding.Mesh(arr, AXES)


def auto_spec(
    n_devices: int,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    ep: int = 1,
    fsdp: int = 1,
) -> MeshSpec:
    """Fill the dp axis with whatever is left after the explicit axes."""
    used = tp * pp * sp * ep * fsdp
    if n_devices % used != 0:
        raise ValueError(f"{n_devices} devices not divisible by tp*pp*sp*ep*fsdp={used}")
    return MeshSpec(dp=n_devices // used, fsdp=fsdp, pp=pp, tp=tp, sp=sp, ep=ep)


def local_mesh(**axes):
    """Mesh over this process's local devices (single-host)."""
    import jax

    return make_mesh(devices=jax.local_devices(), **axes) if axes else make_mesh(
        MeshSpec(dp=len(jax.local_devices()))
    )
