"""Sharding rules: logical array axes -> mesh axes.

Parameters and activations are annotated with *logical* axis names
("batch", "seq", "embed", "heads", "mlp", "vocab", "layers", "experts"); a
rule table maps each to a mesh axis (or None = replicated).  This is the
GSPMD workflow: annotate, let XLA insert collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

from jax.sharding import NamedSharding, PartitionSpec

P = PartitionSpec

# default logical->mesh rules for transformer training
DEFAULT_RULES: Dict[str, Union[str, Tuple[str, ...], None]] = {
    "batch": ("dp", "fsdp"),  # batch sharded over both data axes
    "seq": "sp",
    "embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,  # pp handled by stage stacking, not GSPMD
    "experts": "ep",
    "expert_mlp": "tp",
    # fsdp param sharding: applied to the largest axis of each weight
    "fsdp_shard": "fsdp",
}


def logical_to_spec(
    logical: Sequence[Optional[str]], rules: Optional[Dict] = None
) -> PartitionSpec:
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    for name in logical:
        if name is None:
            out.append(None)
        else:
            out.append(rules.get(name))
    # trim trailing Nones for canonical specs
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(mesh, *logical, rules=None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical, rules))


def shard_pytree(tree: Any, specs: Any, mesh):
    """device_put a pytree according to a matching pytree of PartitionSpecs."""
    import jax

    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def constraint(x, mesh, *logical, rules=None):
    """with_sharding_constraint using logical names (inside jit)."""
    import jax

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_spec(logical, rules))
    )
