"""Sharding rules: logical array axes -> mesh axes.

Parameters and activations are annotated with *logical* axis names
("batch", "seq", "embed", "heads", "mlp", "vocab", "layers", "experts"); a
rule table maps each to a mesh axis (or None = replicated).  This is the
GSPMD workflow: annotate, let XLA insert collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

from jax.sharding import NamedSharding, PartitionSpec

P = PartitionSpec

# default logical->mesh rules for transformer training
DEFAULT_RULES: Dict[str, Union[str, Tuple[str, ...], None]] = {
    "batch": ("dp", "fsdp"),  # batch sharded over both data axes
    "seq": "sp",
    "embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,  # pp handled by stage stacking, not GSPMD
    "experts": "ep",
    "expert_mlp": "tp",
    # fsdp param sharding: applied to the largest axis of each weight
    "fsdp_shard": "fsdp",
}


def logical_to_spec(
    logical: Sequence[Optional[str]], rules: Optional[Dict] = None
) -> PartitionSpec:
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    for name in logical:
        if name is None:
            out.append(None)
        else:
            out.append(rules.get(name))
    # trim trailing Nones for canonical specs
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(mesh, *logical, rules=None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical, rules))


def shard_pytree(tree: Any, specs: Any, mesh):
    """device_put a pytree according to a matching pytree of PartitionSpecs."""
    import jax

    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def constraint(x, mesh, *logical, rules=None):
    """with_sharding_constraint using logical names (inside jit)."""
    import jax

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_spec(logical, rules))
    )


# --- shard-chunk geometry (the resharding read path of sharded checkpoints) ---
#
# A "box" is a device shard's global index as plain data: [[start, stop], ...]
# one pair per dimension (a scalar's box is []).  Boxes serialize to JSON, so
# per-rank shard manifests can describe where each saved chunk lives in the
# global array without pickling slice objects; extract_region stitches any
# requested box back together from whatever chunking the SAVING mesh used —
# which is what lets an 8-way checkpoint restore onto a 6-way mesh.


def index_box(index, shape) -> list:
    """Normalize a shard index (tuple of slices, as jax reports it) into a
    box against the global `shape`."""
    box = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        box.append([start, stop])
    return box


def box_shape(box) -> tuple:
    return tuple(b[1] - b[0] for b in box)


def box_volume(box) -> int:
    n = 1
    for b in box:
        n *= b[1] - b[0]
    return n


def boxes_cover(boxes, shape) -> bool:
    """Do `boxes` exactly tile an array of `shape`?  In-bounds + pairwise
    disjoint + volumes summing to the array's volume is equivalent to an
    exact tiling on an integer grid (the union's volume equals the space's
    and the union is contained in it).  The disjointness check matters:
    volume alone would accept overlapping-but-gapped layouts — e.g. stale
    and fresh manifests with different chunkings in one dir — and a restore
    would then return uninitialized memory for the gap instead of raising."""
    total = 1
    for dim in shape:
        total *= int(dim)
    seen = [
        list(map(list, b))
        for b in {tuple(map(tuple, b)) for b in boxes}
    ]
    for b in seen:
        if any(lo < 0 or hi > int(dim) for (lo, hi), dim in zip(b, shape)):
            return False
    for i, a in enumerate(seen):
        for b in seen[i + 1:]:
            if all(
                max(x[0], y[0]) < min(x[1], y[1]) for x, y in zip(a, b)
            ):
                return False  # a (non-empty) overlap
    return sum(box_volume(b) for b in seen) == total


def extract_region(box, chunks):
    """Assemble the global region `box` from (chunk_box, ndarray) pairs.

    Chunks may be laid out by ANY partitioning of the global array; every
    element of the requested region must be covered (boxes_cover guards
    this at manifest-load time).  This is the topology-portable restore
    primitive: the target mesh asks for its shard's box, and the answer is
    stitched from whichever saved chunks overlap it."""
    import numpy as np

    if not box:  # scalar
        for cbox, arr in chunks:
            return np.asarray(arr).copy()
        raise ValueError("no chunk covers the requested scalar")
    if box_volume(box) == 0:
        # a zero-sized region has no elements to stitch, but still needs
        # the right shape and dtype — the overlap loop below would find no
        # intersecting chunk (every interval is empty) and misread an
        # empty leaf as missing coverage
        for cbox, arr in chunks:
            return np.empty(box_shape(box), dtype=np.asarray(arr).dtype)
        raise ValueError(f"no chunk describes empty region {box}")
    out = None
    for cbox, arr in chunks:
        inter = [
            (max(b[0], c[0]), min(b[1], c[1])) for b, c in zip(box, cbox)
        ]
        if any(lo >= hi for lo, hi in inter):
            continue
        if out is None:
            out = np.empty(box_shape(box), dtype=np.asarray(arr).dtype)
        dst = tuple(
            slice(lo - b[0], hi - b[0]) for (lo, hi), b in zip(inter, box)
        )
        src = tuple(
            slice(lo - c[0], hi - c[0]) for (lo, hi), c in zip(inter, cbox)
        )
        out[dst] = np.asarray(arr)[src]
    if out is None:
        raise ValueError(f"no chunk overlaps requested region {box}")
    return out
