"""Torn-tail-safe replication log for the HA plane (core/head.py).

A warm-standby head journals every replication record it receives from the
active head to an append-only file BEFORE acking it, so "acked watermark"
always means "durably applied here": after a standby restart (or a crash
mid-write) the log replays to exactly the state the active head believes
this standby holds, and the resubscribe watermark picks up from there.

Framing mirrors the head-snapshot torn-write discipline (tmp+rename there,
length+checksum here): each record is

    MAGIC(4) | length(4, LE) | crc32(4, LE) | msgpack body

A record whose header is short, whose body is truncated, or whose checksum
mismatches marks the torn tail — recovery stops THERE, truncates the file
back to the last intact record, and reports the torn flag so the standby can
log the event and re-sync the gap from its acked watermark instead of
applying a corrupt mutation.

Record schema (producer: Head._repl_emit; consumer: apply_record):
    {"t": "full",   "seq": n, "state": <msgpack blob of the snapshot dict>}
    {"t": "tables", "seq": n, "tables": {name: <msgpack blob>}}
    {"t": "kv",     "seq": n, "op": "put"|"del", "ns": s, "key": s,
     "value": bytes, "overwrite": bool}
Heartbeat records ("t": "hb") are liveness-only and are never journaled.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

MAGIC = b"CARL"
_HDR = struct.Struct("<4sII")  # magic, body length, crc32


def _frame(body: bytes) -> bytes:
    return _HDR.pack(MAGIC, len(body), zlib.crc32(body)) + body


def pack_record(record: dict) -> bytes:
    import msgpack

    return _frame(msgpack.packb(record, use_bin_type=True))


def read_records(path: str) -> Tuple[List[dict], int, bool]:
    """Scan the log, returning (intact records, good byte offset, torn?).

    `good offset` is where the first torn/corrupt record starts (== file
    size when the log is clean); everything past it must be truncated.
    """
    import msgpack

    records: List[dict] = []
    try:
        data = open(path, "rb").read()
    except FileNotFoundError:
        return records, 0, False
    off = 0
    torn = False
    n = len(data)
    while off < n:
        if off + _HDR.size > n:
            torn = True
            break
        magic, length, crc = _HDR.unpack_from(data, off)
        body_off = off + _HDR.size
        if magic != MAGIC or body_off + length > n:
            torn = True
            break
        body = data[body_off : body_off + length]
        if zlib.crc32(body) != crc:
            torn = True
            break
        try:
            records.append(msgpack.unpackb(body, raw=False, strict_map_key=False))
        except Exception:
            torn = True
            break
        off = body_off + length
    return records, off, torn


def recover(path: str) -> Tuple[List[dict], bool]:
    """Read the intact prefix and truncate any torn tail in place."""
    records, good, torn = read_records(path)
    if torn:
        with open(path, "r+b") as f:
            f.truncate(good)
    return records, torn


class ReplLogWriter:
    """Append-only journal handle.  flush-per-record (not fsync): the
    durability target is standby-process memory plus an OS-buffered journal
    — a host crash re-syncs from the active head anyway."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def append(self, record: dict) -> None:
        self._f.write(pack_record(record))
        self._f.flush()

    def reset(self) -> None:
        """Start a fresh log (a `full` record supersedes all history)."""
        self._f.close()
        self._f = open(self.path, "wb")

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def apply_record(shadow: Optional[Dict[str, Any]], record: dict) -> Optional[Dict[str, Any]]:
    """Apply one replication record to the standby's shadow state dict (the
    same schema Head._snapshot_state produces).  Returns the new shadow.
    Deltas that arrive before any full state are ignored — the active head
    always opens a fresh subscription with a `full` record."""
    import msgpack

    t = record.get("t")
    if t == "full":
        return msgpack.unpackb(record["state"], raw=False, strict_map_key=False)
    if shadow is None:
        return None
    if t == "tables":
        for name, blob in (record.get("tables") or {}).items():
            shadow[name] = msgpack.unpackb(blob, raw=False, strict_map_key=False)
    elif t == "kv":
        kv = shadow.setdefault("kv", {})
        ns_name = record.get("ns", "")
        if record.get("op") == "put":
            ns = kv.setdefault(ns_name, {})
            if not (record.get("overwrite", True) is False and record["key"] in ns):
                ns[record["key"]] = record.get("value")
        else:
            ns = kv.get(ns_name)
            if ns is not None:
                ns.pop(record["key"], None)
                if not ns:
                    kv.pop(ns_name, None)
    return shadow


def replay(records: List[dict]) -> Tuple[Optional[Dict[str, Any]], int]:
    """Rebuild (shadow state, watermark) from journaled records in order."""
    shadow: Optional[Dict[str, Any]] = None
    watermark = 0
    for rec in records:
        shadow = apply_record(shadow, rec)
        watermark = max(watermark, int(rec.get("seq") or 0))
    return shadow, watermark
