"""In-process sampling profiler (the `ca profile` engine; analogue of the
reference's py-spy-backed `ray stack`/dashboard CPU profiler, but built on
`sys._current_frames()` so it needs no external binary and no ptrace
permission — the sampled process samples itself on a side thread).

`sample_stacks()` runs a wall-clock sampler for a bounded duration and folds
each observed stack into `root;caller;...;leaf -> count` form.  Two renders:
`render_folded()` (flamegraph.pl / speedscope-pasteable text) and
`speedscope_json()` (the sampled-profile speedscope schema, loadable at
https://speedscope.app).  `rusage_probe()` is the cheap point-in-time
CPU/RSS sample the worker attaches to terminal task events so the timeline
carries resource attribution without a profiler run.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

MAX_DURATION_S = 60.0  # a forgotten `ca profile --duration 1e9` must end
MAX_DEPTH = 128


def _frame_label(frame) -> str:
    co = frame.f_code
    return f"{co.co_name} ({os.path.basename(co.co_filename)}:{frame.f_lineno})"


def _fold(frame) -> str:
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < MAX_DEPTH:
        parts.append(_frame_label(frame))
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


def sample_stacks(
    duration_s: float = 2.0,
    hz: float = 100.0,
    all_threads: bool = False,
) -> Dict[str, Any]:
    """Sample this process's stacks for `duration_s` at `hz`.  By default
    only non-sampler, non-daemon-idle *busy candidates* — every thread except
    the sampler itself — are folded; `all_threads=False` additionally drops
    threads parked in the sampler's own wait primitives.  Returns
    {"folded": {stack: count}, "samples": n, "duration_s": d, "hz": hz}.

    The sampler runs on the CALLING thread (callers put it on an executor
    thread; the worker's IO loop must keep serving heartbeats while the
    profile runs)."""
    duration_s = max(0.05, min(float(duration_s), MAX_DURATION_S))
    hz = max(1.0, min(float(hz), 1000.0))
    period = 1.0 / hz
    me = threading.get_ident()
    folded: Dict[str, int] = {}
    n = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            if not all_threads:
                # skip stacks idling in interpreter-internal waits (executor
                # threads between tasks, selector threads): they are noise
                # that buries the busy thread in a merged flame view
                name = frame.f_code.co_name
                if name in ("_worker", "wait", "select", "_run_once", "run"):
                    leaf_file = os.path.basename(frame.f_code.co_filename)
                    if leaf_file in (
                        "threading.py", "selectors.py", "thread.py",
                        "base_events.py", "queue.py",
                    ):
                        continue
            stack = _fold(frame)
            if stack:
                folded[stack] = folded.get(stack, 0) + 1
                n += 1
        time.sleep(period)
    return {"folded": folded, "samples": n, "duration_s": duration_s, "hz": hz}


def render_folded(folded: Dict[str, int], limit: Optional[int] = None) -> str:
    """Folded-stack text, heaviest stacks first (flamegraph.pl input)."""
    rows = sorted(folded.items(), key=lambda kv: -kv[1])
    if limit:
        rows = rows[:limit]
    return "\n".join(f"{stack} {count}" for stack, count in rows)


def top_functions(folded: Dict[str, int], limit: int = 10) -> List[tuple]:
    """(leaf function, self samples) heaviest-first — the `ca profile`
    one-glance summary before the full folded dump."""
    leaf: Dict[str, int] = {}
    for stack, count in folded.items():
        fn = stack.rsplit(";", 1)[-1]
        leaf[fn] = leaf.get(fn, 0) + count
    return sorted(leaf.items(), key=lambda kv: -kv[1])[:limit]


def speedscope_json(
    folded: Dict[str, int], name: str = "ca profile", hz: float = 100.0
) -> Dict[str, Any]:
    """Speedscope "sampled" profile from folded counts.  Each unique stack
    becomes one sample whose weight is its observed share of wall time."""
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []
    samples: List[List[int]] = []
    weights: List[float] = []
    dt = 1.0 / max(hz, 1.0)
    for stack, count in sorted(folded.items(), key=lambda kv: -kv[1]):
        idxs = []
        for label in stack.split(";"):
            i = frame_index.get(label)
            if i is None:
                i = frame_index[label] = len(frames)
                frames.append({"name": label})
            idxs.append(i)
        samples.append(idxs)
        weights.append(count * dt)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": name,
                "unit": "seconds",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        ],
        "exporter": "cluster_anywhere_tpu",
    }


# ------------------------------------------------------------------- rusage


def rusage_probe() -> Dict[str, float]:
    """Point-in-time process resource sample: cumulative CPU seconds and
    max RSS.  Two probes bracketing a task give CPU%% over its wall time
    (process-wide — concurrent tasks on one worker share the number, which
    the timeline view labels as such)."""
    out: Dict[str, float] = {"cpu_s": time.process_time()}
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on linux, bytes on macOS; normalize to bytes
        scale = 1024 if sys.platform != "darwin" else 1
        out["max_rss_bytes"] = float(ru.ru_maxrss) * scale
    except Exception:
        pass
    return out


def rusage_delta(
    t0_wall: float, probe0: Dict[str, float], arena_bytes: Optional[int] = None
) -> Dict[str, float]:
    """Finish-side half of the bracket: CPU%% of wall time since `t0_wall`,
    current max RSS, and (when the caller can see its shm store) live arena
    bytes — the fields attached to terminal task events."""
    p1 = rusage_probe()
    wall = max(time.time() - t0_wall, 1e-9)
    out = {
        "cpu_pct": round(100.0 * (p1["cpu_s"] - probe0.get("cpu_s", 0.0)) / wall, 1),
        "max_rss_bytes": p1.get("max_rss_bytes", 0.0),
    }
    if arena_bytes is not None:
        out["arena_bytes"] = float(arena_bytes)
    return out
